"""paddle.audio.datasets parity (ref: python/paddle/audio/datasets/).

ESC50 parses the release layout (meta/esc50.csv + audio wavs, fold
splits); TESS parses emotion-suffixed wav trees; both route feat_type
through the jax feature extractors and fall back to synthetic waves.
"""
import csv
import os
import wave

import numpy as np
import pytest

from paddle_tpu.audio.datasets import ESC50, TESS, load_wav


def _write_wav(path, samples, sr=16000):
    with wave.open(str(path), "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(sr)
        w.writeframes((np.clip(samples, -1, 1) * 32767)
                      .astype(np.int16).tobytes())


def test_load_wav_roundtrip(tmp_path):
    x = np.sin(np.linspace(0, 20, 1000)).astype(np.float32) * 0.5
    p = tmp_path / "t.wav"
    _write_wav(p, x, sr=8000)
    y, sr = load_wav(p)
    assert sr == 8000 and y.shape == (1000,)
    np.testing.assert_allclose(y, x, atol=1e-3)


def _make_esc50(tmp_path, n=6):
    root = tmp_path / "ESC-50"
    os.makedirs(root / "meta")
    os.makedirs(root / "audio")
    rows = []
    rng = np.random.default_rng(0)
    for i in range(n):
        fname = f"1-{i}-A-{i % 50}.wav"
        _write_wav(root / "audio" / fname,
                   rng.standard_normal(800).astype(np.float32) * 0.1)
        rows.append({"filename": fname, "fold": (i % 5) + 1,
                     "target": i % 50, "category": "x",
                     "esc10": "False", "src_file": "s", "take": "A"})
    with open(root / "meta" / "esc50.csv", "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0]))
        w.writeheader()
        w.writerows(rows)
    return str(root)


def test_esc50_parses_release_and_folds(tmp_path):
    root = _make_esc50(tmp_path, n=10)
    tr = ESC50(mode="train", split=1, data_file=root)
    dv = ESC50(mode="dev", split=1, data_file=root)
    assert len(tr) + len(dv) == 10
    assert len(dv) == 2                      # folds 1 of 5
    x, y = tr[0]
    assert x.dtype == np.float32 and x.shape == (800,)
    assert 0 <= int(y) < 50


def test_esc50_feat_type_melspectrogram(tmp_path):
    root = _make_esc50(tmp_path, n=5)
    ds = ESC50(mode="train", split=1, data_file=root,
               feat_type="melspectrogram", n_fft=256, n_mels=32)
    x, y = ds[0]
    assert x.ndim == 2 and x.shape[0] == 32  # [n_mels, frames]


def test_esc50_synthetic_fallback():
    ds = ESC50(mode="train", n=8, sample_length=512)
    x, y = ds[0]
    assert x.shape == (512,) and 0 <= int(y) < 50
    assert len(ds) == 8


def test_tess_parses_emotion_tree(tmp_path):
    root = tmp_path / "TESS"
    rng = np.random.default_rng(1)
    for actor in ("OAF", "YAF"):
        d = root / actor
        os.makedirs(d)
        for word, emo in (("back", "angry"), ("bar", "happy"),
                          ("base", "sad")):
            _write_wav(d / f"{actor}_{word}_{emo}.wav",
                       rng.standard_normal(400).astype(np.float32) * 0.1)
    tr = TESS(mode="train", n_folds=3, split=1, data_file=str(root))
    dv = TESS(mode="dev", n_folds=3, split=1, data_file=str(root))
    assert len(tr) + len(dv) == 6
    x, y = tr[0]
    assert x.shape == (400,) and 0 <= int(y) < 7


def test_tess_rejects_empty_tree(tmp_path):
    os.makedirs(tmp_path / "empty")
    with pytest.raises(ValueError, match="no .*wav"):
        TESS(data_file=str(tmp_path / "empty"))


def test_tess_synthetic_and_mfcc():
    ds = TESS(mode="train", n=6, sample_length=600, feat_type="mfcc",
              n_mfcc=13, n_fft=256)
    x, y = ds[0]
    assert x.shape[0] == 13
    assert 0 <= int(y) < 7


def test_unknown_feat_type_rejected():
    with pytest.raises(ValueError, match="feat_type"):
        ESC50(feat_type="wavelet")
