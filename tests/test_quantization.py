"""paddle.quantization: fake-quant math, STE gradients, QAT swap+train,
PTQ calibrate+convert, int8 inference parity."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import quantization as Q


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


class TestQuantDequant:
    def test_values_on_grid(self):
        x = paddle.to_tensor(np.array([-1.0, -0.5, 0.0, 0.4, 1.0],
                                      np.float32))
        out = _np(Q.quant_dequant(x, paddle.to_tensor(1.0), bit_length=8))
        # every output is k/127 for integer k; max error <= 0.5/127
        k = out * 127
        assert np.allclose(k, np.round(k), atol=1e-4)
        assert np.abs(out - _np(x)).max() <= 0.5 / 127 + 1e-6

    def test_straight_through_gradient(self):
        x = paddle.to_tensor(np.array([0.3, -0.8], np.float32),
                             stop_gradient=False)
        y = Q.quant_dequant(x, paddle.to_tensor(1.0))
        y.sum().backward()
        assert np.allclose(_np(x.grad), 1.0)  # identity grad (STE)

    def test_per_channel(self):
        w = np.array([[1.0, 10.0], [-2.0, 20.0]], np.float32)  # [in, out]
        s = np.array([2.0, 20.0], np.float32)
        out = _np(Q.quant_dequant(paddle.to_tensor(w), paddle.to_tensor(s),
                                  channel_axis=1))
        assert np.abs(out - w).max() < 20.0 / 127 + 1e-5

    def test_clipping(self):
        x = paddle.to_tensor(np.array([5.0], np.float32))
        out = _np(Q.quant_dequant(x, paddle.to_tensor(1.0)))
        assert np.allclose(out, 1.0, atol=1e-6)  # clipped to scale


class TestQAT:
    def _net(self):
        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(8, 16)
                self.fc2 = nn.Linear(16, 4)

            def forward(self, x):
                return self.fc2(nn.functional.relu(self.fc1(x)))
        return Net()

    def test_quantize_swaps_layers(self):
        net = self._net()
        q = Q.QAT()
        q.quantize(net)
        assert isinstance(net._sub_layers["fc1"], Q.QuantedLinear)
        assert isinstance(net._sub_layers["fc2"], Q.QuantedLinear)

    def test_qat_trains_eager(self):
        net = self._net()
        Q.QAT().quantize(net)
        net.train()
        opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((32, 8)).astype("float32"))
        y = paddle.to_tensor(rng.integers(0, 4, 32))
        lossfn = paddle.nn.CrossEntropyLoss()
        first = None
        for _ in range(25):
            loss = lossfn(net(x), y)
            first = first if first is not None else float(loss)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss) < first * 0.7

    def test_qat_through_engine(self):
        from paddle_tpu.hapi.engine import Engine
        net = self._net()
        Q.QAT().quantize(net)
        net.train()
        eng = Engine(net, loss=paddle.nn.CrossEntropyLoss(),
                     optimizer=paddle.optimizer.Adam(
                         1e-2, parameters=net.parameters()))
        rng = np.random.default_rng(1)
        x = paddle.to_tensor(rng.standard_normal((16, 8)).astype("float32"))
        y = paddle.to_tensor(rng.integers(0, 4, 16))
        l0, _ = eng.train_batch([x], [y])
        for _ in range(10):
            l, _ = eng.train_batch([x], [y])
        assert float(l) < float(l0)
        # the EMA scale buffer updated inside the jitted step
        aq = net._sub_layers["fc1"].activation_quanter
        assert float(_np(aq.scale)) > 0

    def test_quantized_close_to_float(self):
        net = self._net()
        rng = np.random.default_rng(2)
        x = paddle.to_tensor(rng.standard_normal((8, 8)).astype("float32"))
        ref = _np(net(x))
        Q.QAT().quantize(net)
        net.eval()
        # run once in train mode to set activation scales
        net.train()
        net(x)
        net.eval()
        out = _np(net(x))
        assert np.abs(out - ref).max() < 0.15  # int8 error bound


class TestUncalibratedEval:
    def test_eval_before_training_passes_through(self):
        # regression: eval with a never-updated scale (0) collapsed all
        # activations to ~0 output
        paddle.seed(5)
        net = nn.Sequential(nn.Linear(4, 3))
        rng = np.random.default_rng(5)
        x = paddle.to_tensor(rng.standard_normal((2, 4)).astype("float32"))
        ref = _np(net(x))
        Q.QAT().quantize(net)
        net.eval()
        out = _np(net(x))
        # weights still fake-quantized; activations pass through
        assert np.abs(out).max() > 0.01
        assert np.abs(out - ref).max() < 0.05


class TestPTQConvert:
    def test_ptq_calibrate_and_convert(self):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        rng = np.random.default_rng(3)
        x = paddle.to_tensor(rng.standard_normal((64, 8)).astype("float32"))
        ref = _np(net(x))

        ptq = Q.PTQ()
        ptq.quantize(net)
        net.eval()
        net(x)  # calibration forward (observers track absmax)
        ptq.convert(net)
        # converted: Int8InferLinear inside
        inner = [l for _, l in net.named_sublayers()
                 if isinstance(l, Q.Int8InferLinear)]
        assert len(inner) == 2
        out = _np(net(x))
        assert np.abs(out - ref).max() < 0.25
        # sanity: still correlated with float output
        c = np.corrcoef(out.ravel(), ref.ravel())[0, 1]
        assert c > 0.99

    def test_convert_honors_quanter_bits_and_axis(self):
        # regression: convert hardcoded 8-bit per-out-feature regardless of
        # the trained config
        paddle.seed(6)
        net = nn.Sequential(nn.Linear(6, 4))
        cfg = Q.QuantConfig(
            activation=None,
            weight=lambda: Q.FakeQuanterChannelWiseAbsMax(4, channel_axis=0))
        qat = Q.QAT(cfg)
        qat.quantize(net)
        rng = np.random.default_rng(6)
        x = paddle.to_tensor(rng.standard_normal((4, 6)).astype("float32"))
        fq = _np(net(x))  # 4-bit fake-quant reference
        qat.convert(net)
        lin = net._sub_layers["0"]
        assert isinstance(lin, Q.Int8InferLinear)
        assert lin.bit_length == 4 and lin.channel_axis == 0
        out = _np(net(x))
        assert np.allclose(out, fq, atol=1e-5)  # same grid as training

    def test_convert_act_quant_with_per_in_feature_weights(self):
        # regression: act_scale was silently dropped when channel_axis=0
        paddle.seed(8)
        net = nn.Sequential(nn.Linear(6, 4))
        cfg = Q.QuantConfig(
            activation=lambda: Q.FakeQuanterWithAbsMax(8),
            weight=lambda: Q.FakeQuanterChannelWiseAbsMax(8, channel_axis=0))
        qat = Q.QAT(cfg)
        qat.quantize(net)
        net.train()
        rng = np.random.default_rng(8)
        x = paddle.to_tensor(rng.standard_normal((8, 6)).astype("float32"))
        net(x)  # set activation scale
        net.eval()
        fq = _np(net(x))
        qat.convert(net)
        out = _np(net(x))
        assert np.abs(out - fq).max() < 1e-4

    def test_convert_mixed_bit_widths(self):
        # regression: weight bit_length was applied to the activation grid
        paddle.seed(9)
        net = nn.Sequential(nn.Linear(6, 4))
        cfg = Q.QuantConfig(
            activation=lambda: Q.FakeQuanterWithAbsMax(8),
            weight=lambda: Q.FakeQuanterChannelWiseAbsMax(4, channel_axis=1))
        qat = Q.QAT(cfg)
        qat.quantize(net)
        net.train()
        rng = np.random.default_rng(9)
        x = paddle.to_tensor(rng.standard_normal((8, 6)).astype("float32"))
        net(x)
        net.eval()
        fq = _np(net(x))
        qat.convert(net)
        lin = net._sub_layers["0"]
        assert lin.bit_length == 4 and lin.act_bit_length == 8
        out = _np(net(x))
        assert np.abs(out - fq).max() < 1e-4

    def test_observers_freeze_at_convert(self):
        # regression: observers kept updating scales after convert
        paddle.seed(7)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.conv = nn.Conv2D(2, 3, 3, padding=1)
                self.fc = nn.Linear(3 * 4 * 4, 2)

            def forward(self, x):
                h = self.conv(x)
                return self.fc(h.reshape([x.shape[0], -1]))

        net = Net()
        ptq = Q.PTQ()
        ptq.quantize(net)
        net.eval()
        rng = np.random.default_rng(7)
        x = paddle.to_tensor(rng.standard_normal((2, 2, 4, 4))
                             .astype("float32"))
        net(x)  # calibrate
        ptq.convert(net)
        obs = net._sub_layers["conv"].activation_quanter
        s0 = float(_np(obs.scale))
        big = paddle.to_tensor(
            100 * rng.standard_normal((2, 2, 4, 4)).astype("float32"))
        net(big)  # serving traffic must NOT move the scale
        assert float(_np(obs.scale)) == s0

    def test_weight_only_convert_without_calibration(self):
        paddle.seed(4)
        net = nn.Sequential(nn.Linear(6, 3))
        rng = np.random.default_rng(4)
        x = paddle.to_tensor(rng.standard_normal((4, 6)).astype("float32"))
        ref = _np(net(x))
        qat = Q.QAT()
        qat.quantize(net)
        qat.convert(net)  # no calibration -> act_scale None (weight-only)
        out = _np(net(x))
        assert np.abs(out - ref).max() < 0.05
