"""F.interpolate torch-golden parity (ref: paddle.nn.functional
.interpolate) — r4 rewrite: jax.image.resize diverged from the
reference on half-pixel bilinear/bicubic (antialiased downscale),
legacy nearest, and area; now every mode is an exact static weight
matrix per spatial axis.
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F


def _np(t):
    return np.asarray(t.numpy())


CASES_2D = [((2, 3, 8, 10), (5, 7)),      # downscale
            ((2, 3, 5, 6), (9, 11)),      # upscale
            ((1, 1, 4, 4), (4, 4))]       # identity


@pytest.mark.parametrize("shape,size", CASES_2D)
@pytest.mark.parametrize("mode,align", [
    ("nearest", None), ("bilinear", False), ("bilinear", True),
    ("bicubic", False), ("bicubic", True), ("area", None)])
def test_interpolate_2d_matches_torch(shape, size, mode, align):
    x = np.random.default_rng(0).standard_normal(shape).astype(np.float32)
    kw = {} if align is None else {"align_corners": align}
    ours = _np(F.interpolate(paddle.to_tensor(x), size=list(size),
                             mode=mode, **kw))
    ref = tF.interpolate(torch.from_numpy(x), size=size, mode=mode,
                         **kw).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)


def test_interpolate_1d_and_3d_match_torch():
    rng = np.random.default_rng(1)
    x1 = rng.standard_normal((1, 2, 7)).astype(np.float32)
    for mode, align in [("nearest", None), ("linear", False),
                        ("linear", True), ("area", None)]:
        kw = {} if align is None else {"align_corners": align}
        ours = _np(F.interpolate(paddle.to_tensor(x1), size=[4],
                                 mode=mode, **kw))
        ref = tF.interpolate(torch.from_numpy(x1), size=(4,), mode=mode,
                             **kw).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)
    x3 = rng.standard_normal((1, 2, 4, 5, 6)).astype(np.float32)
    for mode, align in [("nearest", None), ("trilinear", False),
                        ("trilinear", True), ("area", None)]:
        kw = {} if align is None else {"align_corners": align}
        ours = _np(F.interpolate(paddle.to_tensor(x3), size=[3, 7, 9],
                                 mode=mode, **kw))
        ref = tF.interpolate(torch.from_numpy(x3), size=(3, 7, 9),
                             mode=mode, **kw).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)


def test_interpolate_scale_factor_and_nhwc():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((2, 3, 6, 8)).astype(np.float32)
    a = _np(F.interpolate(paddle.to_tensor(x), scale_factor=2,
                          mode="bilinear"))
    ref = tF.interpolate(torch.from_numpy(x), scale_factor=2,
                         mode="bilinear", align_corners=False).numpy()
    np.testing.assert_allclose(a, ref, rtol=1e-5, atol=1e-5)
    # NHWC layout produces the transposed result
    xl = np.transpose(x, (0, 2, 3, 1)).copy()
    b = _np(F.interpolate(paddle.to_tensor(xl), scale_factor=2,
                          mode="bilinear", data_format="NHWC"))
    np.testing.assert_allclose(np.transpose(b, (0, 3, 1, 2)), ref,
                               rtol=1e-5, atol=1e-5)


def test_interpolate_align_mode_1():
    """paddle's align_mode=1 (src = i*scale, no half-pixel shift) —
    checked against the direct formula."""
    x = np.arange(8, dtype=np.float32).reshape(1, 1, 8)
    out = _np(F.interpolate(paddle.to_tensor(x), size=[5], mode="linear",
                            align_corners=False, align_mode=1))
    src = np.arange(5) * (8 / 5)
    lo = np.floor(src).astype(int)
    hi = np.minimum(lo + 1, 7)
    w = src - lo
    ref = (x[0, 0, lo] * (1 - w) + x[0, 0, hi] * w).astype(np.float32)
    np.testing.assert_allclose(out[0, 0], ref, rtol=1e-6)


def test_interpolate_gradients_flow():
    import jax
    import jax.numpy as jnp
    x = jnp.asarray(np.random.default_rng(3).standard_normal((1, 2, 4, 4)),
                    jnp.float32)

    def loss(a):
        o = F.interpolate(paddle.to_tensor(a), size=[8, 8],
                          mode="bilinear")
        return jnp.sum(o._value ** 2)

    g = jax.grad(loss)(x)
    assert float(jnp.abs(g).max()) > 0


def test_interpolate_unknown_mode_rejected():
    with pytest.raises(ValueError, match="mode"):
        F.interpolate(paddle.to_tensor(np.zeros((1, 1, 4, 4), np.float32)),
                      size=[2, 2], mode="lanczos")


@pytest.mark.parametrize("in_len,out_len", [(21, 19), (25, 11), (130, 7)])
def test_area_large_sizes_match_torch(in_len, out_len):
    """Integer window bounds: float floor/ceil drifts at these sizes
    (e.g. 21->19 truncated the last window) — review-confirmed bug."""
    x = np.random.default_rng(4).standard_normal(
        (1, 2, in_len)).astype(np.float32)
    ours = _np(F.interpolate(paddle.to_tensor(x), size=[out_len],
                             mode="area"))
    ref = tF.interpolate(torch.from_numpy(x), size=(out_len,),
                         mode="area").numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


def test_nearest_align_corners_ties_round_up():
    """in=3,out=5 puts source positions on exact .5: the reference
    rounds UP (floor(x+0.5)), numpy's round would tie-to-even."""
    x = np.asarray([[[10.0, 20.0, 30.0]]], np.float32)
    out = _np(F.interpolate(paddle.to_tensor(x), size=[5],
                            mode="nearest", align_corners=True))
    np.testing.assert_array_equal(out[0, 0], [10, 20, 20, 30, 30])


def test_bicubic_ignores_align_mode():
    x = np.random.default_rng(5).standard_normal(
        (1, 1, 6, 6)).astype(np.float32)
    a = _np(F.interpolate(paddle.to_tensor(x), size=[9, 9],
                          mode="bicubic", align_mode=0))
    b = _np(F.interpolate(paddle.to_tensor(x), size=[9, 9],
                          mode="bicubic", align_mode=1))
    np.testing.assert_array_equal(a, b)
