"""Test harness config: force a virtual 8-device CPU mesh.

Distributed tests run on 8 virtual CPU devices
(xla_force_host_platform_device_count) per SURVEY.md §4. The environment's
sitecustomize registers a remote-TPU ("axon") PJRT backend whose lazy client
connect can stall CPU-only test runs — deregister it before the first jax
op so tests never touch the tunnel.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import jax._src.xla_bridge as xb  # noqa: E402

# the axon register hook sets jax_platforms via config (overrides env)
jax.config.update("jax_platforms", "cpu")
for reg in ("_backend_factories", "backend_factories"):
    d = getattr(xb, reg, None)
    if isinstance(d, dict):
        d.pop("axon", None)

assert jax.devices()[0].platform == "cpu"
assert jax.device_count() == 8, jax.devices()


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_global_mesh():
    """Isolate tests from global-mesh leakage: a mesh set by one test
    (shard_model/set_mesh) must not change another test's sharding
    constraints or pipeline routing."""
    from paddle_tpu.distributed import mesh as mesh_mod
    mesh_mod._global_mesh = None
    yield
    mesh_mod._global_mesh = None


# Persistent XLA compilation cache: OPT-IN via PADDLE_TPU_TEST_CACHE=
# <dir>. It used to be on by default ("cuts suite wall time
# several-fold" across runs), but on this round's box RELOADING a
# donated Engine train-step program from the cache crashes jaxlib
# 0.4.37 (deterministic SIGSEGV/SIGABRT in the deserialized
# executable; the COLD compile of the identical program is fine —
# repro + diagnosis in R6_NOTES.md). A crashed suite reports ~40% of
# its tests, so default-off wins; compiled executables now stay alive
# in memory across modules instead (see _clear_jax_caches_per_module).
_cache_dir = os.environ.get("PADDLE_TPU_TEST_CACHE")
if _cache_dir:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy variant with a cheaper sibling in the default run; "
        "included when PADDLE_TPU_RUN_SLOW=1")
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection resilience suite "
        "(standalone: pytest -m chaos; campaign stage chaos_smoke)")


def pytest_collection_modifyitems(config, items):
    if os.environ.get("PADDLE_TPU_RUN_SLOW") == "1":
        return
    skip = pytest.mark.skip(reason="slow variant (set PADDLE_TPU_RUN_SLOW=1)")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


def jit_forward(m, *xs):
    """Shared helper: run a Layer's forward as ONE jitted functional call
    (the production Engine/jit path) and return plain arrays."""
    from paddle_tpu.nn.layer import functional_call
    from paddle_tpu.tensor import Tensor
    params, buffers = m.raw_state()

    @jax.jit
    def fwd(p, b, *a):
        out = functional_call(m, p, b, *[Tensor(x) for x in a])
        if isinstance(out, (tuple, list)):
            return tuple(t._value for t in out)
        return out._value
    return fwd(params, buffers, *xs)


# The tracing-heavy tests allocate millions of short-lived containers;
# CPython's default gen-0 threshold (700) makes the collector run
# constantly inside jax tracing on this 1-core box. Collections still
# happen — at module boundaries below — so memory stays bounded.
import gc  # noqa: E402

gc.set_threshold(200_000, 100, 100)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Module-boundary housekeeping. jax.clear_caches() used to run here
    so late tests didn't slow down 2-3x under accumulated tracing
    caches, with recompiles served by the persistent on-disk cache.
    With that cache off by default (reloads crash this box's jaxlib —
    see above), dropping executables would force full recompiles of
    every model per module; keeping them alive is far cheaper, and the
    box has the memory for it. gc still runs to keep the tracing-heavy
    modules' garbage bounded. Opt back into the old behavior together
    with PADDLE_TPU_TEST_CACHE."""
    yield
    if os.environ.get("PADDLE_TPU_TEST_CACHE"):
        jax.clear_caches()
    import gc
    gc.collect()


# -- fleet-stage metrics export (campaign canary gate) -----------------------
# The fleet chaos tests (test_fleet_serving / test_fleet_tracing)
# register each FleetRouter's registry here; at session end the merged
# snapshot lands as metrics.json in $BENCH_TELEMETRY_DIR — the
# artifact tools/tpu_campaign.py's fleet canary gate diffs against the
# committed golden (tools/golden/fleet_chaos_metrics.json). A no-op
# outside the campaign (env unset) or when no fleet test ran.
fleet_stage_registries = []


@pytest.fixture(scope="session", autouse=True)
def _fleet_stage_metrics_export():
    yield
    out_dir = os.environ.get("BENCH_TELEMETRY_DIR")
    if not out_dir or not fleet_stage_registries:
        return
    from paddle_tpu.observability.metrics import MetricsRegistry
    from paddle_tpu.observability.trace import report_all
    merged = MetricsRegistry()
    for reg in fleet_stage_registries:
        try:
            merged.merge(reg.snapshot())
        except Exception:  # noqa: BLE001 — one bad registry must not
            pass           # cost the whole stage its artifact
    merged.dump(os.path.join(out_dir, "metrics.json"),
                extra={"recompile_report": report_all(),
                       "stage": "fleet_chaos"})
