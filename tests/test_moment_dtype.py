"""bf16 Adam moments (moment_dtype='bfloat16') — parity vs fp32 states.

ref parity: python/paddle/optimizer/adamw.py multi_precision path (the
reference's reduced-precision optimizer-state story); here the mechanism
is bf16 moment storage with stochastic rounding (see
optimizer.py:_sround_bf16) to halve optimizer HBM traffic on TPU.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.optimizer.optimizer import _sround_bf16


def test_sround_unbiased():
    key = jax.random.PRNGKey(0)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal(2048), jnp.float32) * 0.01
    acc = jnp.zeros_like(x)
    n = 128
    for i in range(n):
        acc = acc + _sround_bf16(x, jax.random.fold_in(key, i)).astype(
            jnp.float32)
    err = float(jnp.max(jnp.abs(acc / n - x)) / jnp.max(jnp.abs(x)))
    assert err < 3e-3


def test_sround_small_increment_ema():
    """(1-b2)=1e-3 increments sit below bf16 resolution: nearest rounding
    freezes the EMA, stochastic rounding must track it."""
    key = jax.random.PRNGKey(1)
    v32 = jnp.float32(1.0)
    vbf = jnp.bfloat16(1.0)
    for i in range(1500):
        v32 = 0.999 * v32 + 0.001 * 2.0
        vnew = 0.999 * vbf.astype(jnp.float32) + 0.001 * 2.0
        vbf = _sround_bf16(vnew, jax.random.fold_in(key, i))
    assert abs(float(vbf) - float(v32)) / float(v32) < 0.03


def _train_quadratic(moment_dtype, steps=120):
    paddle.seed(0)
    target = jnp.asarray(
        np.random.default_rng(3).standard_normal((8, 8)), jnp.float32)
    layer = paddle.nn.Linear(8, 8)
    opt = paddle.optimizer.AdamW(learning_rate=0.05, weight_decay=0.0,
                                 parameters=layer.parameters(),
                                 moment_dtype=moment_dtype)
    x = jnp.eye(8, dtype=jnp.float32)
    for _ in range(steps):
        out = layer(paddle.Tensor(x))
        loss = ((out - paddle.Tensor(target)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float(loss._value if hasattr(loss, "_value") else loss)


def test_bf16_moments_converge_like_fp32():
    l32 = _train_quadratic(None)
    lbf = _train_quadratic("bfloat16")
    # both drive the quadratic bowl to ~0; bf16 states must not stall
    assert lbf < max(5 * l32, 1e-2), (lbf, l32)


def test_bf16_moments_state_dtype():
    layer = paddle.nn.Linear(4, 4)
    opt = paddle.optimizer.AdamW(parameters=layer.parameters(),
                                 moment_dtype="bfloat16")
    st = opt.init_state({"w": jnp.zeros((4, 4), jnp.float32)})
    assert st["m"]["w"].dtype == jnp.bfloat16
    assert st["v"]["w"].dtype == jnp.bfloat16


def test_bf16_moments_engine_step():
    """The jitted Engine step carries bf16 moments without dtype drift
    (signature-stable across steps — no recompile, donation-safe)."""
    from paddle_tpu.hapi.engine import Engine
    paddle.seed(0)
    model = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                                 paddle.nn.Linear(16, 4))
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters(),
                                 moment_dtype="bfloat16")
    eng = Engine(model, loss=paddle.nn.CrossEntropyLoss(), optimizer=opt)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((4, 8)),
                    jnp.float32)
    y = jnp.asarray([0, 1, 2, 3])
    losses = []
    for _ in range(6):
        loss, _ = eng.train_batch([x], [y])
        losses.append(float(loss))
    leaves = jax.tree_util.tree_leaves(eng._opt_state["m"])
    assert all(l.dtype == jnp.bfloat16 for l in leaves)
    assert losses[-1] < losses[0]


def test_invalid_moment_dtype_rejected():
    with pytest.raises(ValueError):
        paddle.optimizer.Adam(parameters=[], moment_dtype="float16")


def test_fleet_strategy_bf16_moments():
    """DistributedStrategy.bf16_moments wires moment_dtype through
    fleet.distributed_optimizer (ref: strategy-driven optimizer config)."""
    from paddle_tpu.distributed import fleet
    strat = fleet.DistributedStrategy()
    strat.bf16_moments = True
    fleet.init(is_collective=True, strategy=strat)
    try:
        layer = paddle.nn.Linear(4, 4)
        opt = paddle.optimizer.AdamW(parameters=layer.parameters())
        opt = fleet.fleet_obj.distributed_optimizer(opt)
        st = opt.init_state({"w": jnp.zeros((4, 4), jnp.float32)})
        assert st["m"]["w"].dtype == jnp.bfloat16

        sgd = paddle.optimizer.SGD(parameters=layer.parameters())
        with pytest.raises(ValueError, match="Adam"):
            fleet.fleet_obj.distributed_optimizer(sgd)
        # NAdam subclasses Adam but lacks the rounding store path — must
        # be rejected, not silently fp32 (review fix)
        nadam = paddle.optimizer.NAdam(parameters=layer.parameters())
        with pytest.raises(ValueError, match="Adam"):
            fleet.fleet_obj.distributed_optimizer(nadam)
    finally:
        # the fleet singleton is process-wide: restore a default strategy
        fleet.init(is_collective=True,
                   strategy=fleet.DistributedStrategy())
