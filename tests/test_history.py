"""Telemetry history plane (observability/history.py), anomaly
sentinel (observability/sentinel.py), the metrics_diff --history
--at/--vs gate and the fleet_top renderer.

Pins the ISSUE-11 contracts:

- downsampling ladder: raw at scrape cadence, 10s/60s rungs holding
  the LAST cumulative value per bucket at its real last-update
  timestamp (a bucket-start stamp would smuggle future increments
  behind a past timestamp);
- range/rate/quantile-over-time reads, including windows that reach
  past the raw ring into the rungs;
- torn-snapshot reload: a snapshot TRUNCATED AT EVERY BYTE OFFSET
  reloads without crashing, never duplicates a sample, and drops at
  most the tail (the journal-fuzz discipline, applied to history);
- registry_snapshot_at + metrics_diff --history --at/--vs: one
  archive, any two instants, the canary gate runs on it;
- sentinel: quiet through warmup + steady state, fires on a genuine
  excursion (with a parseable fleet_anomaly flight dump + counters),
  re-arms only after the signal clears; offline replay over a saved
  archive; compile-delta signal fires on ANY recompile.
"""
import json
import os
import sys

import pytest

from paddle_tpu.observability.history import HistoryStore
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.sentinel import AnomalySentinel
from paddle_tpu.observability import flightrec

T0 = 1_000_000.0


def _filled_store(n=120, spike_after=None, interval=1.0):
    """A registry driven n scrapes: counter +10/scrape, gauge ramp,
    latency histogram flat at 10ms (spiking to 300ms past
    ``spike_after``)."""
    reg = MetricsRegistry()
    c = reg.counter("w_total")
    g = reg.gauge("w_depth")
    h = reg.histogram("w_seconds")
    hs = HistoryStore(reg, interval_s=interval, raw_samples=64,
                      rungs=((10.0, 32), (60.0, 32)))
    for i in range(n):
        c.inc(10)
        g.set(i)
        h.observe(0.30 if spike_after is not None and i >= spike_after
                  else 0.01)
        hs.scrape(now=T0 + i * interval)
    return reg, hs


class TestHistoryStore:
    def test_ladder_shapes_and_query(self):
        _, hs = _filled_store(n=120)
        assert set(hs.keys()) == {"w_total", "w_depth", "w_seconds"}
        raw = hs.query("w_total", res="raw")
        assert len(raw) == 64          # ring bound, not 120
        assert raw[-1]["v"] == 1200    # cumulative
        ten = hs.query("w_total", res="10s")
        assert len(ten) <= 32
        # rung samples are stamped at their real last-update ts and
        # hold the bucket's LAST cumulative value
        for s in ten:
            exact = hs.query("w_total", t0=s["t"], t1=s["t"],
                             res="raw")
            if exact:   # inside raw reach
                assert exact[0]["v"] == s["v"]
        # gauges carry min/max per bucket
        g10 = hs.query("w_depth", res="10s")[-1]
        assert g10["min"] <= g10["v"] <= g10["max"]

    def test_maybe_scrape_cadence(self):
        reg = MetricsRegistry()
        reg.counter("w_total").inc()
        hs = HistoryStore(reg, interval_s=1.0)
        assert hs.maybe_scrape(now=T0) is not None
        assert hs.maybe_scrape(now=T0 + 0.5) is None
        assert hs.maybe_scrape(now=T0 + 1.5) is not None
        assert hs.scrapes == 2

    def test_rate_and_reset_tolerance(self):
        _, hs = _filled_store(n=120)
        r = hs.rate("w_total", 20.0)
        assert r == pytest.approx(10.0, rel=0.2)
        # a counter reset (process restart) must not go negative
        reg = MetricsRegistry()
        c = reg.counter("w_total")
        hs2 = HistoryStore(reg, interval_s=1.0)
        for i, v in enumerate((100, 200, 300)):
            c.value = v
            hs2.scrape(now=T0 + i)
        c.value = 50   # restart
        hs2.scrape(now=T0 + 3)
        c.value = 150
        hs2.scrape(now=T0 + 4)
        inc = hs2.increase("w_total", T0, T0 + 4)
        assert inc == 300   # 100+100 pre-reset + 100 post, never -150

    def test_rate_reaches_past_raw_ring_into_rungs(self):
        # 120 scrapes, raw ring 64: a 100s window must use the rungs
        _, hs = _filled_store(n=120)
        r = hs.rate("w_total", 100.0, now=T0 + 119)
        assert r == pytest.approx(10.0, rel=0.25)

    def test_quantile_over_time_sees_only_the_window(self):
        _, hs = _filled_store(n=120, spike_after=100)
        now = T0 + 119
        q_spike = hs.quantile_over_time("w_seconds", 0.5, 15.0,
                                        now=now)
        q_clean = hs.quantile_over_time("w_seconds", 0.99, 15.0,
                                        now=T0 + 90)
        assert q_spike > 0.1      # the spike window reads high
        assert q_clean < 0.05     # the clean window never sees it
        # unknown / non-histogram series answer None, never raise
        assert hs.quantile_over_time("nope", 0.99, 5.0) is None
        assert hs.quantile_over_time("w_total", 0.99, 5.0) is None

    def test_registry_snapshot_at(self):
        _, hs = _filled_store(n=120)
        snap = hs.registry_snapshot_at(T0 + 80)
        assert snap["metrics"]["w_total"]["value"] == 810
        hist = snap["metrics"]["w_seconds"]
        assert hist["count"] == 81 and len(hist["counts"]) \
            == len(hist["bounds"]) + 1
        # before the first sample: series omitted, not invented
        assert hs.registry_snapshot_at(T0 - 10)["metrics"] == {}


class TestSnapshotPersistence:
    def test_roundtrip(self, tmp_path):
        _, hs = _filled_store(n=50)
        p = str(tmp_path / "hist.json")
        hs.save(p)
        hs2 = HistoryStore.load(p)
        assert hs2.load_dropped == 0
        assert hs2.keys() == hs.keys()
        for key in hs.keys():
            for res in ("raw", "10s", "60s"):
                assert hs2.query(key, res=res) == hs.query(key,
                                                           res=res)
        assert hs2.rate("w_total", 20.0, now=T0 + 49) \
            == hs.rate("w_total", 20.0, now=T0 + 49)

    def test_torn_snapshot_every_byte_offset(self, tmp_path):
        """The journal-fuzz discipline: truncate at EVERY byte; reload
        never crashes, never duplicates a sample, drops at most the
        tail (sample sets are always a subset of the full archive's,
        and line-prefix truncation loses whole tail chunks only)."""
        _, hs = _filled_store(n=12)   # small → every offset is cheap
        p = str(tmp_path / "hist.json")
        hs.save(p)
        with open(p, "rb") as f:
            data = f.read()
        full = HistoryStore.load(p)
        full_samples = {
            (key, res): [tuple(s) for s in
                         full._series[key].rings[res]]
            for key in full.keys()
            for res in full._series[key].rings}
        tp = str(tmp_path / "torn.json")
        for cut in range(len(data) + 1):
            with open(tp, "wb") as f:
                f.write(data[:cut])
            store = HistoryStore.load(tp)     # must never raise
            for key in store.keys():
                ser = store._series[key]
                for res, ring in ser.rings.items():
                    got = [tuple(s) for s in ring]
                    ref = full_samples.get((key, res), [])
                    # exactly-once: a chunk is whole or absent —
                    # which also rules out any duplicated sample
                    assert got == [] or got == ref, \
                        f"cut={cut} {key}/{res}"
            # monotone tail-loss: what loads is a prefix-subset of
            # the full chunk set
            loaded = {(k, r) for k in store.keys()
                      for r, ring in store._series[k].rings.items()
                      if ring}
            assert loaded <= set(full_samples)

    def test_truncated_tail_drops_are_counted(self, tmp_path):
        _, hs = _filled_store(n=12)
        p = str(tmp_path / "h.json")
        hs.save(p)
        data = open(p, "rb").read()
        with open(p, "wb") as f:
            f.write(data[:len(data) - 5])
        store = HistoryStore.load(p)
        assert store.load_dropped == 1


class TestMetricsDiffHistoryMode:
    def _run(self, argv):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import importlib
        md = importlib.import_module("metrics_diff")
        return md.main(argv)

    def test_at_vs_two_instants_and_gate(self, tmp_path, capsys):
        _, hs = _filled_store(n=120, spike_after=100)
        p = str(tmp_path / "hist.json")
        hs.save(p)
        rc = self._run([
            "--history", p, "--at", str(T0 + 50), "--vs", "-0",
            "--quiet"])
        out = json.loads(capsys.readouterr().out.strip()
                         .splitlines()[-1])
        assert rc == 0 and out["ok"]
        # T0+50 is past the raw ring's reach: the 10s rung answers
        # with its latest sample AT-OR-BEFORE the instant (v=500 @
        # t=49) — conservative, never leaking future increments
        assert out["counters"]["w_total"]["a"] == 500
        assert out["counters"]["w_total"]["b"] == 1200
        # the gate trips on the spike between the two instants
        # (relative offsets anchor on the archive's earliest RETAINED
        # sample — the first 10s bucket's last update, T0+9 here)
        rc = self._run([
            "--history", p, "--at", "+85", "--vs", "-0", "--quiet",
            "--fail-on", "w_seconds:p99>100%"])
        assert rc == 1
        # and stays quiet across the clean span
        rc = self._run([
            "--history", p, "--at", "+10", "--vs", "+80", "--quiet",
            "--fail-on", "w_seconds:p99>100%"])
        capsys.readouterr()
        assert rc == 0

    def test_relative_offsets(self, tmp_path, capsys):
        _, hs = _filled_store(n=20)
        p = str(tmp_path / "hist.json")
        hs.save(p)
        rc = self._run(["--history", p, "--at", "+0", "--vs", "-0",
                        "--quiet"])
        out = json.loads(capsys.readouterr().out.strip()
                         .splitlines()[-1])
        assert rc == 0
        assert out["counters"]["w_total"]["a"] == 10
        assert out["counters"]["w_total"]["b"] == 200


def _sentinel_signals():
    return [{"name": "lat_p99", "kind": "quantile",
             "series": "w_seconds", "q": 0.99, "window_s": 5.0,
             "direction": "high"},
            {"name": "rate_low", "kind": "rate", "series": "w_total",
             "window_s": 5.0, "direction": "low"}]


class TestSentinel:
    def test_quiet_then_fires_once_and_rearms(self, tmp_path,
                                              monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        flightrec.get_recorder().clear()
        reg = MetricsRegistry()
        h = reg.histogram("w_seconds")
        c = reg.counter("w_total")
        hs = HistoryStore(reg, interval_s=1.0)
        sen = AnomalySentinel(hs, signals=_sentinel_signals(),
                              registry=reg, warmup=8,
                              min_consecutive=2, eval_interval_s=0.0)
        # steady state: quiet
        for i in range(40):
            h.observe(0.01)
            c.inc(10)
            hs.scrape(now=T0 + i)
            sen.evaluate(now=T0 + i)
        assert sen.fired_total == 0 and sen.alerting() == []
        # excursion: latency x30 — fires ONCE, stays alerting
        for i in range(40, 52):
            h.observe(0.3)
            c.inc(10)
            hs.scrape(now=T0 + i)
            sen.evaluate(now=T0 + i)
        assert sen.fired_total == 1
        assert "lat_p99" in sen.alerting()
        fired = reg.get("fleet_anomaly_fired_total",
                        {"signal": "lat_p99"})
        active = reg.get("fleet_anomaly_active",
                         {"signal": "lat_p99"})
        assert fired.value == 1 and active.value == 1
        # flight dump: parseable, tagged, carries the signal
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_fleet_anomaly")]
        assert dumps
        doc = json.load(open(tmp_path / dumps[0]))
        assert doc["reason"] == "fleet_anomaly"
        assert doc["signal"] == "lat_p99"
        assert isinstance(doc["recent"], list)
        # recovery: signal clears, re-arms, a SECOND excursion fires
        # a second (fresh) excursion record
        for i in range(52, 90):
            h.observe(0.01)
            c.inc(10)
            hs.scrape(now=T0 + i)
            sen.evaluate(now=T0 + i)
        assert sen.alerting() == []
        assert active.value == 0
        for i in range(90, 102):
            h.observe(0.3)
            c.inc(10)
            hs.scrape(now=T0 + i)
            sen.evaluate(now=T0 + i)
        assert sen.fired_total == 2

    def test_throughput_collapse_fires_low_direction(self):
        reg = MetricsRegistry()
        c = reg.counter("w_total")
        hs = HistoryStore(reg, interval_s=1.0)
        # a SHORT rate window so the collapse hits the band as a
        # cliff: a band with per-eval adaptation absorbs slow ramps
        # by design — the sentinel is a cliff detector, the SLO
        # burn-rate layer owns slow budget spend
        sig = dict(_sentinel_signals()[1], window_s=2.0)
        sen = AnomalySentinel(hs, signals=[sig],
                              warmup=8, min_consecutive=2, z=3.0,
                              eval_interval_s=0.0, flight=False)
        for i in range(40):
            c.inc(100)
            hs.scrape(now=T0 + i)
            sen.evaluate(now=T0 + i)
        assert sen.fired_total == 0
        for i in range(40, 52):          # collapse: +0/s
            hs.scrape(now=T0 + i)
            sen.evaluate(now=T0 + i)
        assert sen.fired_total == 1
        assert sen.state()["rate_low"]["alert"]

    def test_demand_gate_suppresses_idle_collapse(self):
        """A client going quiet must NOT read as a throughput
        collapse: with demand_gate=fleet_pending, zero-demand windows
        evaluate as no-data (alert clears); the same collapse WITH
        pending work still fires."""
        reg = MetricsRegistry()
        c = reg.counter("w_total")
        g = reg.gauge("fleet_pending")
        hs = HistoryStore(reg, interval_s=1.0)
        sig = {"name": "tok_low", "kind": "rate", "series": "w_total",
               "window_s": 2.0, "direction": "low",
               "demand_gate": "fleet_pending"}
        sen = AnomalySentinel(hs, signals=[sig], warmup=8,
                              min_consecutive=2, z=3.0,
                              eval_interval_s=0.0, flight=False)
        for i in range(40):
            c.inc(100)
            g.set(3)
            hs.scrape(now=T0 + i)
            sen.evaluate(now=T0 + i)
        # demand stops WITH the throughput: suppressed, stays quiet
        g.set(0)
        for i in range(40, 60):
            hs.scrape(now=T0 + i)
            st = sen.evaluate(now=T0 + i)
        assert sen.fired_total == 0
        assert st["tok_low"]["value"] is None
        # demand and traffic return long enough for the band to
        # re-tighten, then throughput collapses WITH work pending:
        # a real regression, and it fires
        g.set(3)
        for i in range(60, 95):
            c.inc(100 if i < 85 else 0)
            hs.scrape(now=T0 + i)
            sen.evaluate(now=T0 + i)
        assert sen.fired_total == 1

    def test_compile_delta_fires_on_any_increase(self):
        reg = MetricsRegistry()
        hs = HistoryStore(reg, interval_s=1.0)
        counts = {"r0": {"decode": 1, "prefill_16": 1}}
        report = {"replicas": counts, "unexpected_retraces": 0}
        sen = AnomalySentinel(
            hs, signals=[{"name": "recompiles", "kind": "delta"}],
            compile_fn=lambda: report, eval_interval_s=0.0,
            flight=False)
        hs.scrape(now=T0)
        sen.evaluate(now=T0)           # baseline
        sen.evaluate(now=T0 + 1)
        assert sen.fired_total == 0
        counts["r0"]["prefill_32"] = 1  # a mid-wave recompile
        sen.evaluate(now=T0 + 2)
        assert sen.fired_total == 1
        assert sen.state()["recompiles"]["alert"]

    def test_replay_offline(self, tmp_path):
        reg = MetricsRegistry()
        h = reg.histogram("w_seconds")
        c = reg.counter("w_total")
        hs = HistoryStore(reg, interval_s=1.0)
        for i in range(60):
            h.observe(0.3 if i >= 45 else 0.01)
            c.inc(10)
            hs.scrape(now=T0 + i)
        p = str(tmp_path / "arch.json")
        hs.save(p)
        firings = AnomalySentinel.replay(
            HistoryStore.load(p), signals=[_sentinel_signals()[0]],
            warmup=8, min_consecutive=2)
        assert [f["signal"] for f in firings] == ["lat_p99"]
        # a clean archive replays quiet
        reg2 = MetricsRegistry()
        h2 = reg2.histogram("w_seconds")
        hs2 = HistoryStore(reg2, interval_s=1.0)
        for i in range(60):
            h2.observe(0.01)
            hs2.scrape(now=T0 + i)
        assert AnomalySentinel.replay(
            hs2, signals=[_sentinel_signals()[0]], warmup=8,
            min_consecutive=2) == []


class TestFleetTopRender:
    def test_render_offline_snapshot(self, tmp_path):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import importlib
        ft = importlib.import_module("fleet_top")
        reg = MetricsRegistry()
        reg.counter("fleet_tokens_out_total").inc(500)
        reg.histogram("fleet_ttft_seconds").observe(0.02, count=10)
        hs = HistoryStore(reg, interval_s=1.0)
        for i in range(10):
            hs.scrape(now=T0 + i)
        hs.save(str(tmp_path / "history_snapshot.json"))
        with open(tmp_path / "tenants.json", "w") as f:
            json.dump({"tracked": 1, "capacity": 8, "evictions": 0,
                       "error_bound": 0,
                       "totals": {"tokens_in": 9, "tokens_out": 500,
                                  "queue_wait_s": 0.1,
                                  "kv_page_s": 1.0, "requests": 3},
                       "tenants": [{"tenant": "acme", "weight": 509,
                                    "err": 0, "tokens_in": 9,
                                    "tokens_out": 500,
                                    "queue_wait_s": 0.1,
                                    "kv_page_s": 1.0,
                                    "requests": 3}]}, f)
        with open(tmp_path / "health.json", "w") as f:
            json.dump({"queue_depth": 0, "pending": 0, "lost": [],
                       "slo": {"alerting": []},
                       "anomaly": {"alerting": ["ttft_p99"]},
                       "replicas": {"r0": {
                           "state": "serving", "incarnation": 2,
                           "queued": 0, "running": 1,
                           "free_pages": 7, "scrape_age_s": 0.01,
                           "lost": False, "quarantined": False}}}, f)
        frame = ft.collect_snapshot(str(tmp_path))
        text = ft.render(frame)
        assert "acme" in text
        assert "anomaly:ttft_p99" in text
        assert "r0" in text and "serving" in text
        # main() offline mode end to end
        rc = ft.main(["--snapshot", str(tmp_path)])
        assert rc == 0
