"""Speculative decoding: draft-propose / one-dispatch-verify (ISSUE 20).

Pins the round-20 contracts (docs/performance.md "Speculative
decoding"):

- THE invariant: speculation may change latency, never tokens — ON vs
  OFF streams are token-exact for GPT and Llama/GQA across greedy and
  top-k sampling and fp32/bf16/int8 KV dtypes (each axis covered on
  both models; the full cross product rides the campaign's spec_smoke
  + bench serve rungs). The verify dispatch applies the target
  model's own per-(request, token-index) seeded sampler to every
  folded lane, so an accepted draft IS the token plain decode would
  have emitted;
- proposers: the zero-weight prompt-lookup (ngram) fallback
  self-extends through the match so tight cycles accept at 100%; the
  draft-model proposer runs a real tiny model one-behind the target
  (its state derived fresh from target state each round — rejected
  drafts need no draft-side rewind). Draft quality is a latency knob,
  never a correctness one;
- arming: PADDLE_TPU_SPEC_DECODE / spec_decode= arms the engine,
  warmup() pre-traces the folded verify program, and an armed-but-
  never-warmed engine takes the plain decode path for every dispatch
  — a never-armed engine is byte-identical to a spec-off one (no
  serve_spec_* series even registered);
- zero-recompile: a warmed spec engine serves accepting AND rejecting
  dispatches with frozen compile counts;
- fleet: fleet_spec_* counters delta-fold engine stats off heartbeats
  (restart-reset-safe), per-tenant draft/accepted-token accounting
  feeds fleet_top's SPEC_ACC column, and crash-mid-spec-decode
  failover stays token-exact with speculation ON everywhere.

`pytest -m chaos` selects the fleet classes; the campaign's
fleet_chaos_smoke stage runs exactly that (the router registries
registered here fold into the canary golden's fleet_spec_* series —
the fleet_spec_accepted_total<50% canary's non-vacuity).

Engine/warmup tracing dominates this module's wall time, so waves are
single-bucket and assertions share engines wherever the contracts
allow.
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config as _gpt_cfg
from paddle_tpu.nlp.llama import LlamaForCausalLM, \
    _resolve_config as _llama_cfg
from paddle_tpu.nlp.serving import ServingEngine
from paddle_tpu.nlp.speculative import DraftModelProposer, \
    NgramProposer, _ngram_propose, make_proposer
from paddle_tpu.resilience import faults
from paddle_tpu.serving_fleet import FleetRouter, InprocReplica

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NEW_TOK = 8
PS = 16


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_gpt_cfg("gpt-tiny"))
    m.eval()
    return m


@pytest.fixture(scope="module")
def llama_model():
    paddle.seed(0)
    m = LlamaForCausalLM(_llama_cfg("llama-tiny"))
    m.eval()
    return m


def wave(n=6, seed=0, vocab=256, lo=20, hi=28):
    """Seeded random prompts, every length inside prefill bucket 32.
    Tiny greedy models collapse into short cycles within a few steps,
    which is what makes the ngram acceptance assertions non-vacuous."""
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab,
                         (int(rng.integers(lo, hi)),)).astype(np.int32)
            for _ in range(n)]


def _engine(model, spec=True, **kw):
    d = dict(max_slots=2, page_size=PS, max_seq_len=64,
             steps_per_dispatch=4, num_pages=64, spec_decode=spec,
             spec_k=4, spec_draft="ngram")
    d.update(kw)
    return ServingEngine(model, **d)


def _run(model, spec, prompts, new_tok=NEW_TOK, **kw):
    eng = _engine(model, spec, **kw)
    eng.warmup(buckets=[len(p) for p in prompts], decode=True)
    out = eng.generate(prompts, max_new_tokens=new_tok)
    sp = eng.health().get("spec")
    eng.close()
    return out, sp, eng


def _counter(reg, name, **labels):
    c = reg.get(name, labels or None)
    return 0 if c is None else int(c.value)


# -- ngram proposer (pure host lookup) -----------------------------------


class TestNgramPropose:
    def test_cycle_self_extends_to_full_k(self):
        # the drafted tokens join the working context, so one match
        # unrolls a short cycle out to the full K — this is what buys
        # ~100% acceptance once greedy decode settles into a loop
        ctx = [7, 1, 2, 3, 1, 2, 3, 1, 2, 3]
        assert _ngram_propose(ctx, 6, -1) == [1, 2, 3, 1, 2, 3]

    def test_most_recent_occurrence_wins(self):
        # [5, 9] occurred twice; the draft continues the LATER one
        ctx = [5, 9, 1, 5, 9, 2, 5, 9]
        assert _ngram_propose(ctx, 1, -1) == [2]

    def test_no_match_pads(self):
        assert _ngram_propose([1, 2, 3, 4], 3, -1) == [-1, -1, -1]
        assert _ngram_propose([], 2, -1) == [-1, -1]

    def test_proposer_pads_dead_slots(self, gpt_model):
        eng = _engine(gpt_model)
        try:
            p = eng._spec
            assert isinstance(p, NgramProposer) and p.kind == "ngram"
            drafts = p.propose(eng)
            assert drafts.shape == (eng.max_slots, eng.spec_k)
            assert (drafts == eng.pad_token_id).all()  # no live slots
        finally:
            eng.close()

    def test_make_proposer_rejects_unknown(self, gpt_model):
        eng = _engine(gpt_model)
        try:
            with pytest.raises(ValueError):
                make_proposer(eng, "not-a-draft")
        finally:
            eng.close()


# -- engine: the token-exactness invariant -------------------------------


# every sampler and every KV dtype covered on BOTH models (pairing,
# not cross product — each engine pays ~10s of warmup tracing, and
# the remaining combos ride spec_smoke + the bench serve rungs)
EXACT_CASES = [
    ("gpt", {}, None),
    ("gpt", dict(temperature=0.8, top_k=4, seed=11), "bfloat16"),
    ("gpt", dict(temperature=0.8, top_k=4, seed=11), "int8"),
    ("llama", {}, "int8"),
    ("llama", dict(temperature=0.8, top_k=4, seed=11), None),
    ("llama", {}, "bfloat16"),
]


class TestTokenExactness:
    @pytest.mark.parametrize(
        "which,sampler,cache_dtype", EXACT_CASES,
        ids=[f"{w}-{'topk' if s else 'greedy'}-{d or 'fp32'}"
             for w, s, d in EXACT_CASES])
    def test_on_vs_off_token_exact(self, which, sampler, cache_dtype,
                                   request):
        """Speculation may never change tokens — only latency.
        Llama-tiny is the GQA coverage (kv_heads < heads)."""
        model = request.getfixturevalue(f"{which}_model")
        kw = dict(sampler)
        if cache_dtype:
            kw["cache_dtype"] = cache_dtype
        prompts = wave()
        on, sp, _ = _run(model, True, prompts, **kw)
        off, _, _ = _run(model, False, prompts, **kw)
        assert on == off, "speculative decode changed tokens"
        assert sp["proposed"] > 0 and sp["dispatches"] > 0, \
            "wave never took the spec path — the check was vacuous"

    def test_acceptance_nonvacuous_frozen_counts_no_leaks(
            self, gpt_model):
        """Greedy long decode settles into cycles the prompt-lookup
        proposer predicts — acceptance must be genuinely nonzero (a
        rejecting-only run would pass exactness trivially), compile
        counts stay frozen across accepting AND rejecting dispatches,
        and every page returns to the free list after close()."""
        prompts = wave()
        eng = _engine(gpt_model, spec_k=8)
        eng.warmup(buckets=[len(p) for p in prompts], decode=True)
        frozen = eng.compile_counts()
        out1 = eng.generate(prompts, max_new_tokens=24)
        out2 = eng.generate(prompts, max_new_tokens=24)
        assert out1 == out2, "speculative decode is nondeterministic"
        assert eng.compile_counts() == frozen
        assert eng.tracer.unexpected_retraces() == 0
        sp = eng.health()["spec"]
        assert sp["accepted"] > 0 and sp["acceptance_rate"] > 0
        assert sp["armed"] and sp["k"] == 8 and sp["draft"] == "ngram"
        assert _counter(eng.registry, "serve_spec_accepted_total") \
            == sp["accepted"]
        eng.close()
        assert eng.free_page_count == eng.num_pages - 1, \
            "speculative rewind leaked pages"


# -- engine: draft-model proposer ----------------------------------------


class TestDraftModelProposer:
    def test_self_draft_token_exact_high_acceptance(self, gpt_model):
        """The target as its own draft: the propose pass predicts the
        verify pass near-perfectly (greedy), so acceptance lands high
        — and the streams are STILL bit-identical to plain decode
        (draft quality is a latency knob, never a correctness one)."""
        prompts = wave()
        on, sp, _ = _run(gpt_model, True, prompts, new_tok=12,
                         spec_draft=gpt_model)
        off, _, _ = _run(gpt_model, False, prompts, new_tok=12)
        assert on == off
        assert sp["draft"] == "draft"
        assert sp["acceptance_rate"] > 0.5, \
            "an identical-weight draft must accept heavily"

    def test_random_draft_still_token_exact(self, gpt_model):
        """A draft with UNRELATED weights (fresh random init) proposes
        junk — acceptance drops, tokens do not move."""
        paddle.seed(123)
        junk = GPTForCausalLM(_gpt_cfg("gpt-tiny"))
        junk.eval()
        prompts = wave(4)
        on, sp, _ = _run(gpt_model, True, prompts, spec_draft=junk)
        off, _, _ = _run(gpt_model, False, prompts)
        assert on == off, "a bad draft changed tokens"
        assert sp["proposed"] > 0

    def test_vocab_mismatch_rejected(self, gpt_model):
        eng = _engine(gpt_model)
        try:
            cfg = _gpt_cfg("gpt-tiny")
            cfg.vocab_size *= 2
            paddle.seed(0)
            bad = GPTForCausalLM(cfg)
            bad.eval()
            with pytest.raises(ValueError, match="vocab"):
                DraftModelProposer(eng, bad)
        finally:
            eng.close()


# -- engine: arming, kill switch, dormancy -------------------------------


class TestArming:
    def test_env_knobs_arm_and_configure(self, gpt_model, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SPEC_DECODE", "1")
        monkeypatch.setenv("PADDLE_TPU_SPEC_K", "3")
        eng = ServingEngine(gpt_model, max_slots=2, page_size=PS,
                            max_seq_len=64, steps_per_dispatch=4)
        try:
            assert eng._spec is not None and eng.spec_k == 3
            assert eng.health()["spec"]["armed"] is False  # no warmup
        finally:
            eng.close()

    def test_kill_switch_disables_cleanly(self, gpt_model, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SPEC_DECODE", "0")
        eng = ServingEngine(gpt_model, max_slots=2, page_size=PS,
                            max_seq_len=64, steps_per_dispatch=4)
        try:
            assert eng._spec is None
            assert eng.health().get("spec") is None
            # never-armed: no serve_spec_* series even registered, so
            # the metrics surface is byte-identical to pre-round-20
            assert eng.registry.get("serve_spec_proposed_total") is None
        finally:
            eng.close()

    def test_armed_unwarmed_takes_plain_path_token_exact(
            self, gpt_model):
        """Warmup that skips decode leaves _warmed_spec unset: every
        dispatch must route through plain decode (no verify trace
        mid-traffic) and still match the spec-off stream."""
        prompts = wave(3)
        eng = _engine(gpt_model)
        try:
            eng.warmup(buckets=[len(p) for p in prompts], decode=False)
            assert not eng._warmed_spec
            out = eng.generate(prompts, max_new_tokens=NEW_TOK)
            sp = eng.health()["spec"]
            assert sp["dispatches"] == 0 and sp["proposed"] == 0
        finally:
            eng.close()
        off, _, _ = _run(gpt_model, False, prompts)
        assert out == off

    def test_spec_k_validated(self, gpt_model):
        with pytest.raises(ValueError, match="spec_k"):
            _engine(gpt_model, spec_k=0)


# -- fleet: counters, tenancy, failover (campaign chaos) -----------------


def _spec_fleet(model, n=2, router_kw=None, **engine_kw):
    engines = [_engine(model, **engine_kw) for _ in range(n)]
    lens = sorted({len(p) for p in wave(9)})
    for e in engines:
        e.warmup(buckets=lens, decode=True)
    frozen = [e.compile_counts() for e in engines]
    reps = [InprocReplica(f"r{i}", e) for i, e in enumerate(engines)]
    router = FleetRouter(reps, **dict(router_kw or {}))
    # register for the session-end metrics.json export the campaign's
    # fleet canary gate diffs (conftest._fleet_stage_metrics_export) —
    # this is what makes fleet_spec_* nonzero in the golden
    import conftest
    conftest.fleet_stage_registries.append(router.registry)
    return router, reps, engines, frozen


@pytest.mark.chaos
class TestFleetSpec:
    def test_counters_tenancy_and_restart_fold(self, gpt_model):
        """fleet_spec_* delta-folds off heartbeats (restart-safe), and
        per-tenant draft/accepted tokens account — the rows fleet_top
        renders as SPEC_ACC."""
        prompts = wave(6)
        router, reps, engines, frozen = _spec_fleet(gpt_model, n=2,
                                                    spec_k=8)
        try:
            rids = [router.submit(p, 24, tenant="team-s")
                    for p in prompts]
            res = {r["id"]: r for r in router.run_to_completion()}
            assert all(res[i]["status"] == "ok" for i in rids)
            router._scrape_all()
            reg = router.registry
            assert _counter(reg, "fleet_spec_proposed_total") > 0
            assert _counter(reg, "fleet_spec_accepted_total") > 0
            assert _counter(reg, "fleet_spec_dispatches_total") > 0
            drafted = _counter(reg, "fleet_spec_draft_tokens_total",
                               tenant="team-s")
            accepted = _counter(reg, "fleet_spec_accepted_tokens_total",
                                tenant="team-s")
            assert drafted > 0 and 0 < accepted <= drafted
            t = router.tenants.report()
            row = [r for r in t["tenants"]
                   if r["tenant"] == "team-s"][0]
            assert row["spec_proposed"] == drafted
            assert row["spec_accepted"] == accepted
            # restart-reset fold: a stat that went BACKWARDS means a
            # respawn — fold the new absolute value, never a negative
            p0 = _counter(reg, "fleet_spec_proposed_total")
            router._fold_spec("zz", {"spec": {"proposed": 5,
                                              "accepted": 2,
                                              "dispatches": 1}})
            assert _counter(reg, "fleet_spec_proposed_total") == p0 + 5
            router._fold_spec("zz", {})          # inventory cleared
            assert "zz" not in router._spec_seen
        finally:
            router.close()

    def test_failover_token_exact_mid_spec_decode(self, gpt_model):
        """Crash a replica mid-wave with speculation ON everywhere:
        every request completes token-exact vs a spec-OFF golden (the
        failover continuation re-proposes at its destination against
        rewound state), and compile counts stay frozen."""
        prompts = wave(6)
        refs, _, _ = _run(gpt_model, False, prompts)
        router, reps, engines, frozen = _spec_fleet(gpt_model, n=2)
        try:
            assert router.generate(prompts, max_new_tokens=NEW_TOK) \
                == refs
            with faults.scenario(("replica_crash", {"replica": "r1"})):
                outs = router.generate(prompts, max_new_tokens=NEW_TOK)
            assert outs == refs, \
                "failover with speculation ON must stay token-exact"
            assert reps[1].state == "dead"
            for i, eng in enumerate(engines):
                assert eng.compile_counts() == frozen[i]
            assert router.compile_report()["unexpected_retraces"] == 0
        finally:
            router.close()
