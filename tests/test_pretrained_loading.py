"""Pretrained-weight import recipe (ref: paddle.vision.models pretrained
loading / paddlenlp PretrainedModel.from_pretrained).

Offline story: reference checkpoints (.pdparams pickles) or paddle_tpu
saves load via pretrained='path' / from_pretrained(..., pretrained_path=)
with strict full-match semantics and forward parity; pretrained=True
raises with the convert-and-load recipe.
"""
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle


def _ref_pdparams(state, path):
    """Write a reference-framework-style .pdparams: a plain pickle of
    {name: ndarray} (what paddle.save(state_dict) produces)."""
    blob = {k: np.asarray(v._value if hasattr(v, "_value") else v)
            for k, v in state.items()}
    with open(path, "wb") as f:
        pickle.dump(blob, f, protocol=2)


def test_resnet18_pretrained_path_roundtrip(tmp_path):
    from paddle_tpu.vision.models import resnet18
    paddle.seed(0)
    src = resnet18(num_classes=10)
    src.eval()
    ck = str(tmp_path / "resnet18.pdparams")
    _ref_pdparams(src.state_dict(), ck)

    paddle.seed(123)                     # different init
    dst = resnet18(pretrained=ck, num_classes=10)
    dst.eval()
    x = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((2, 3, 32, 32))
        .astype(np.float32))
    np.testing.assert_allclose(dst(x).numpy(), src(x).numpy(),
                               rtol=1e-5, atol=1e-5)


def test_resnet18_pretrained_true_gives_recipe():
    from paddle_tpu.vision.models import resnet18
    with pytest.raises(NotImplementedError, match="pdparams"):
        resnet18(pretrained=True)


@pytest.mark.slow
def test_vgg_pretrained_path(tmp_path):
    from paddle_tpu.vision.models import vgg11
    paddle.seed(1)
    src = vgg11(num_classes=4)
    src.eval()
    ck = str(tmp_path / "vgg11.pdparams")
    _ref_pdparams(src.state_dict(), ck)
    paddle.seed(99)
    dst = vgg11(pretrained=ck, num_classes=4)
    dst.eval()
    x = paddle.to_tensor(np.ones((1, 3, 32, 32), np.float32))
    np.testing.assert_allclose(dst(x).numpy(), src(x).numpy(),
                               rtol=1e-5, atol=1e-5)


def test_pretrained_shape_mismatch_loud(tmp_path):
    from paddle_tpu.vision.models import resnet18
    paddle.seed(0)
    src = resnet18(num_classes=10)
    ck = str(tmp_path / "r18.pdparams")
    _ref_pdparams(src.state_dict(), ck)
    with pytest.raises(ValueError, match="shape mismatch"):
        resnet18(pretrained=ck, num_classes=7)   # head differs


def test_pretrained_partial_checkpoint_refused(tmp_path):
    from paddle_tpu.vision.models import resnet18
    paddle.seed(0)
    src = resnet18(num_classes=10)
    state = dict(src.state_dict())
    state.pop(sorted(state)[0])                  # drop one parameter
    ck = str(tmp_path / "partial.pdparams")
    _ref_pdparams(state, ck)
    with pytest.raises(ValueError, match="partial load"):
        resnet18(pretrained=ck, num_classes=10)


def test_bert_base_from_pretrained_roundtrip(tmp_path):
    from paddle_tpu.nlp import BertModel
    paddle.seed(2)
    src = BertModel.from_config_name(
        "bert-base-uncased", num_hidden_layers=2, hidden_size=64,
        num_attention_heads=4, intermediate_size=128, vocab_size=500,
        max_position_embeddings=64)
    src.eval()
    ck = str(tmp_path / "bert.pdparams")
    _ref_pdparams(src.state_dict(), ck)

    paddle.seed(77)
    dst = BertModel.from_pretrained(
        "bert-base-uncased", pretrained_path=ck, num_hidden_layers=2,
        hidden_size=64, num_attention_heads=4, intermediate_size=128,
        vocab_size=500, max_position_embeddings=64)
    dst.eval()
    ids = paddle.to_tensor(np.arange(16, dtype=np.int64)[None, :] % 500)
    seq_s, pool_s = src(ids)
    seq_d, pool_d = dst(ids)
    np.testing.assert_allclose(seq_d.numpy(), seq_s.numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(pool_d.numpy(), pool_s.numpy(),
                               rtol=1e-5, atol=1e-5)


def test_bert_from_pretrained_path_first_form(tmp_path):
    from paddle_tpu.nlp import BertModel
    paddle.seed(3)
    src = BertModel.from_config_name(
        "bert-base-uncased", num_hidden_layers=1, hidden_size=32,
        num_attention_heads=2, intermediate_size=64, vocab_size=200,
        max_position_embeddings=32)
    ck = str(tmp_path / "b.pdparams")
    _ref_pdparams(src.state_dict(), ck)
    dst = BertModel.from_pretrained(
        ck, config_name="bert-base-uncased", num_hidden_layers=1,
        hidden_size=32, num_attention_heads=2, intermediate_size=64,
        vocab_size=200, max_position_embeddings=32)
    assert dst.config.hidden_size == 32
    # checkpoint path without a config name is an actionable error
    with pytest.raises(ValueError, match="config_name"):
        BertModel.from_pretrained(ck)


def test_bert_from_pretrained_no_weights_recipe():
    from paddle_tpu.nlp import BertModel
    with pytest.raises(NotImplementedError, match="pdparams"):
        BertModel.from_pretrained("bert-base-uncased",
                                  num_hidden_layers=1, hidden_size=32,
                                  num_attention_heads=2,
                                  intermediate_size=64)


def test_strict_refusal_leaves_model_untouched(tmp_path):
    """The partial-load check must run BEFORE mutation: a refused load
    may not leave the model half-overwritten."""
    from paddle_tpu.serialization import load_into
    from paddle_tpu.vision.models import LeNet
    paddle.seed(6)
    model = LeNet()
    before = {k: np.asarray(v._value).copy()
              for k, v in model.state_dict().items()}
    paddle.seed(7)
    other = LeNet()
    state = dict(other.state_dict())
    state.pop(sorted(state)[-1])
    ck = str(tmp_path / "part.pdparams")
    _ref_pdparams(state, ck)
    with pytest.raises(ValueError, match="partial load"):
        load_into(model, ck)
    for k, v in model.state_dict().items():
        np.testing.assert_array_equal(np.asarray(v._value), before[k])


def test_from_pretrained_conflicting_sources(tmp_path):
    from paddle_tpu.nlp import BertModel
    ck = str(tmp_path / "a.pdparams")
    _ref_pdparams({}, ck)
    with pytest.raises(ValueError, match="exactly one"):
        BertModel.from_pretrained(ck, pretrained_path="b.pdparams",
                                  config_name="bert-tiny")


def test_gpt_from_pretrained_exists():
    from paddle_tpu.nlp import GPTForCausalLM
    assert hasattr(GPTForCausalLM, "from_pretrained")


def test_load_into_accepts_paddle_tpu_save(tmp_path):
    """The same entry point loads our own save format (sniffed)."""
    from paddle_tpu.serialization import load_into
    from paddle_tpu.vision.models import LeNet
    paddle.seed(4)
    src = LeNet()
    p = str(tmp_path / "lenet.pt")
    paddle.save(src.state_dict(), p)
    paddle.seed(55)
    dst = LeNet()
    load_into(dst, p)
    for k, v in src.state_dict().items():
        np.testing.assert_array_equal(np.asarray(v._value),
                                      np.asarray(dst.state_dict()[k]._value))
