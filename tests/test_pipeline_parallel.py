"""Pipeline parallel == sequential forward/backward (SURVEY §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.distributed.fleet.pipeline import (
    pipeline_apply, stack_stage_params, PipelineLayer, LayerDesc)


def _mesh(pp=4, dp=2):
    devs = np.array(jax.devices()[:pp * dp]).reshape(dp, pp)
    return Mesh(devs, ("dp", "pp"))


def _stage_fn(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def _make_params(key, n_stages, d):
    ks = jax.random.split(key, n_stages)
    per = [{"w1": jax.random.normal(k, (d, d)) * 0.3,
            "b1": jnp.zeros((d,)),
            "w2": jax.random.normal(jax.random.fold_in(k, 1), (d, d)) * 0.3,
            "b2": jnp.zeros((d,))} for k in ks]
    return per


class TestPipelineApply:
    def test_forward_matches_sequential(self):
        d, n_stages, batch = 8, 4, 8
        per = _make_params(jax.random.PRNGKey(0), n_stages, d)
        x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))

        ref = x
        for p in per:
            ref = _stage_fn(p, ref)

        mesh = _mesh(pp=n_stages, dp=2)
        out = pipeline_apply(mesh, stack_stage_params(per), x, _stage_fn,
                             n_micro=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_grads_match_sequential(self):
        d, n_stages, batch = 4, 4, 8
        per = _make_params(jax.random.PRNGKey(2), n_stages, d)
        stacked = stack_stage_params(per)
        x = jax.random.normal(jax.random.PRNGKey(3), (batch, d))
        mesh = _mesh(pp=n_stages, dp=2)

        def loss_pipe(sp):
            return jnp.sum(pipeline_apply(mesh, sp, x, _stage_fn,
                                          n_micro=2) ** 2)

        def loss_seq(sp):
            h = x
            for i in range(n_stages):
                h = _stage_fn(jax.tree_util.tree_map(lambda a: a[i], sp), h)
            return jnp.sum(h ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
        g_seq = jax.jit(jax.grad(loss_seq))(stacked)
        for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                        jax.tree_util.tree_leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_single_stage_identity(self):
        d = 4
        per = _make_params(jax.random.PRNGKey(4), 1, d)
        x = jax.random.normal(jax.random.PRNGKey(5), (4, d))
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("pp",))
        out = pipeline_apply(mesh, stack_stage_params(per), x, _stage_fn,
                             n_micro=2)
        ref = _stage_fn(per[0], x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestPipelineLayer:
    def test_layer_matches_sequential(self):
        from paddle_tpu.nn.layers_common import Linear
        from paddle_tpu.tensor import Tensor
        from paddle_tpu.distributed import mesh as mesh_mod

        blocks = [Linear(8, 8) for _ in range(4)]
        pipe = PipelineLayer(layers=blocks)
        x = Tensor(jax.random.normal(jax.random.PRNGKey(6), (8, 8)))

        old = mesh_mod._global_mesh
        try:
            mesh_mod._global_mesh = None
            ref = pipe(x)  # sequential path
            mesh_mod._global_mesh = _mesh(pp=4, dp=2)
            out = pipe(x, n_micro=4)
        finally:
            mesh_mod._global_mesh = old
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.asarray(ref._value),
                                   rtol=2e-5, atol=2e-5)

    def test_unequal_stage_split_raises(self):
        from paddle_tpu.nn.layers_common import Linear
        pipe = PipelineLayer(layers=[Linear(4, 4) for _ in range(3)])
        with pytest.raises(ValueError):
            pipe._stage_slices(2)


class TestPipelineLayerGrads:
    def test_eager_backward_populates_block_grads(self):
        """Regression: grads must land on the live block Parameters when
        running pipelined under a pp mesh, matching the sequential path."""
        from paddle_tpu.nn.layers_common import Linear
        from paddle_tpu.tensor import Tensor
        from paddle_tpu.distributed import mesh as mesh_mod

        blocks = [Linear(8, 8) for _ in range(4)]
        pipe = PipelineLayer(layers=blocks)
        x = Tensor(jax.random.normal(jax.random.PRNGKey(7), (8, 8)))

        old = mesh_mod._global_mesh
        try:
            mesh_mod._global_mesh = None
            (pipe(x) ** 2).sum().backward()
            ref_grads = [np.asarray(p.grad._value) for p in pipe.parameters()]
            for p in pipe.parameters():
                p.clear_grad()
            mesh_mod._global_mesh = _mesh(pp=4, dp=2)
            (pipe(x, n_micro=4) ** 2).sum().backward()
        finally:
            mesh_mod._global_mesh = old
        for p, ref in zip(pipe.parameters(), ref_grads):
            assert p.grad is not None, "grad missing on live block param"
            np.testing.assert_allclose(np.asarray(p.grad._value), ref,
                                       rtol=1e-4, atol=1e-4)

    def test_shared_layer_desc_ties_weights(self):
        """SharedLayerDesc with the same key must alias the weight Tensor
        (ref pp_layers.py shared embedding/lm-head tying)."""
        from paddle_tpu.distributed.fleet.pipeline import SharedLayerDesc
        from paddle_tpu.nn.layers_common import Linear

        pipe = PipelineLayer(layers=[
            SharedLayerDesc("tied", Linear, 4, 4),
            LayerDesc(Linear, 4, 4),
            SharedLayerDesc("tied", Linear, 4, 4),
            LayerDesc(Linear, 4, 4),
        ])
        assert pipe.blocks[0].weight is pipe.blocks[2].weight


class TestGPTPipe:
    """The flagship THROUGH the pipeline (VERDICT r1 #4): real decoder
    blocks, pp==sequential numerics, and a full train step on a dp x pp
    mesh."""

    def _cfg(self):
        from paddle_tpu.nlp.gpt import GPTConfig
        return GPTConfig(vocab_size=128, hidden_size=32,
                         num_hidden_layers=4, num_attention_heads=2,
                         max_position_embeddings=32,
                         hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0,
                         use_flash_attention=False)

    def test_pp_matches_sequential(self):
        import paddle_tpu as paddle
        from paddle_tpu.nlp.gpt import GPTForCausalLMPipe
        from paddle_tpu.nn.layer import functional_call
        from paddle_tpu.tensor import Tensor
        paddle.seed(0)
        pipe = GPTForCausalLMPipe(self._cfg())
        ids = paddle.to_tensor(np.random.RandomState(0)
                               .randint(0, 128, (4, 16)).astype("int32"))
        out_seq = pipe(ids)  # off-mesh -> sequential blocks
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("dp", "pp"))
        pipe.mesh, pipe.n_micro = mesh, 2
        params, buffers = pipe.raw_state()

        def fwd(p, a):
            return functional_call(pipe, p, buffers, Tensor(a))._value
        with mesh:
            out_pp = jax.jit(fwd)(params, ids._value)
        np.testing.assert_allclose(np.asarray(out_pp),
                                   np.asarray(out_seq), atol=2e-5)

    def test_train_step_on_dp_pp_mesh(self):
        import paddle_tpu as paddle
        from paddle_tpu.nlp.gpt import (GPTForCausalLMPipe,
                                        GPTPretrainingCriterion)
        from paddle_tpu.hapi.engine import Engine
        paddle.seed(0)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("dp", "pp"))
        pipe = GPTForCausalLMPipe(self._cfg(), mesh=mesh, n_micro=2)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=pipe.parameters())
        eng = Engine(pipe, loss=GPTPretrainingCriterion(), optimizer=opt,
                     mesh=mesh)
        rng = np.random.RandomState(1)
        ids = paddle.to_tensor(rng.randint(0, 128, (4, 16)).astype("int32"))
        lbl = paddle.to_tensor(rng.randint(0, 128, (4, 16)).astype("int32"))
        with mesh:
            losses = [float(eng.train_batch([ids], [lbl])[0])
                      for _ in range(3)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]


class TestInterleavedSchedule:
    """Interleaved virtual-stage pipeline (ref: fleet pp_utils
    num_virtual_pipeline_stages / Megatron interleaved 1F1B): the CPU
    accounting below pins the tick math; the equivalence tests prove the
    compiled ring schedule computes exactly the sequential model."""

    def test_schedule_accounting(self):
        """Coverage + causality + no double-booking, enumerated over the
        full (device, tick) grid — the measurable bubble model."""
        from paddle_tpu.distributed.fleet.pipeline import (
            interleaved_schedule, pipeline_cost)
        for p, v, m in [(4, 2, 8), (4, 2, 6), (2, 3, 4), (4, 1, 8)]:
            cost = pipeline_cost(p, m, v)
            ticks = cost["ticks"]
            seen = {}
            for t in range(ticks):
                for s in range(p):
                    j, c = interleaved_schedule(t - s, p, v)
                    if 0 <= j < m:
                        # each (micro, chunk, device) slot exactly once
                        key = (j, c, s)
                        assert key not in seen
                        seen[key] = t
            # every microbatch visits every global stage exactly once
            assert len(seen) == m * v * p
            # causality: chunk c at device s happens right after device
            # s-1; chunk c+1 at device 0 right after chunk c left s=p-1
            for (j, c, s), t in seen.items():
                if s > 0:
                    assert seen[(j, c, s - 1)] == t - 1
                elif c > 0:
                    assert seen[(j, c - 1, p - 1)] == t - 1
            # bubble shrinks ~v-fold vs FThenB at p | m
            if m % p == 0 and v > 1:
                fb = pipeline_cost(p, m, 1)["bubble_fraction"]
                il = cost["bubble_fraction"]
                assert il < fb
                assert abs(il - (p - 1) / (m * v + p - 1)) < 1e-9

    def test_interleaved_forward_matches_sequential(self):
        d, p, v, batch = 8, 4, 2, 8
        per = _make_params(jax.random.PRNGKey(6), p * v, d)
        x = jax.random.normal(jax.random.PRNGKey(7), (batch, d))
        ref = x
        for prm in per:
            ref = _stage_fn(prm, ref)
        mesh = _mesh(pp=p, dp=2)
        out = pipeline_apply(mesh, stack_stage_params(per), x, _stage_fn,
                             n_micro=4, n_virtual=v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_interleaved_tail_group(self):
        """n_micro not divisible by p: the padded group's ghost slots
        must not corrupt real outputs."""
        d, p, v = 4, 4, 2
        per = _make_params(jax.random.PRNGKey(8), p * v, d)
        x = jax.random.normal(jax.random.PRNGKey(9), (6, d))
        ref = x
        for prm in per:
            ref = _stage_fn(prm, ref)
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("pp",))
        out = pipeline_apply(mesh, stack_stage_params(per), x, _stage_fn,
                             n_micro=6, n_virtual=v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_interleaved_grads_match_sequential(self):
        d, p, v, batch = 4, 2, 2, 8
        per = _make_params(jax.random.PRNGKey(10), p * v, d)
        stacked = stack_stage_params(per)
        x = jax.random.normal(jax.random.PRNGKey(11), (batch, d))
        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("pp",))

        def loss_pipe(sp):
            return jnp.sum(pipeline_apply(mesh, sp, x, _stage_fn,
                                          n_micro=4, n_virtual=v) ** 2)

        def loss_seq(sp):
            h = x
            for i in range(p * v):
                h = _stage_fn(jax.tree_util.tree_map(lambda a: a[i], sp),
                              h)
            return jnp.sum(h ** 2)

        g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
        g_seq = jax.jit(jax.grad(loss_seq))(stacked)
        for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                        jax.tree_util.tree_leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_pipeline_layer_virtual_stages(self):
        from paddle_tpu.nn.layers_common import Linear
        from paddle_tpu.tensor import Tensor
        from paddle_tpu.distributed import mesh as mesh_mod
        import paddle_tpu as paddle
        paddle.seed(12)
        blocks = [Linear(6, 6) for _ in range(8)]
        layer = PipelineLayer(blocks, num_virtual_pipeline_stages=2)
        x = Tensor(jax.random.normal(jax.random.PRNGKey(13), (8, 6)))
        ref = layer(x)                       # off-mesh: sequential
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("pp",))
        old = mesh_mod._global_mesh
        mesh_mod._global_mesh = mesh
        try:
            out = layer(x, n_micro=4)
        finally:
            mesh_mod._global_mesh = old
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.asarray(ref._value),
                                   rtol=2e-5, atol=2e-5)
