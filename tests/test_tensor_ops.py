"""Tensor op numerics vs numpy golden values (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def n(t):
    return np.asarray(t.numpy())


class TestCreation:
    def test_to_tensor_dtypes(self):
        assert paddle.to_tensor(1).dtype == np.int64
        assert paddle.to_tensor(1.5).dtype == np.float32
        assert paddle.to_tensor(True).dtype == np.bool_
        assert paddle.to_tensor([1, 2]).dtype == np.int64
        assert paddle.to_tensor(np.zeros(3)).dtype == np.float64

    def test_creation_ops(self):
        assert n(paddle.zeros([2, 3])).shape == (2, 3)
        assert n(paddle.ones([2])).tolist() == [1, 1]
        assert n(paddle.full([2], 7)).tolist() == [7, 7]
        assert n(paddle.arange(5)).tolist() == [0, 1, 2, 3, 4]
        assert np.allclose(n(paddle.linspace(0, 1, 5)), np.linspace(0, 1, 5))
        assert np.allclose(n(paddle.eye(3)), np.eye(3))
        assert np.allclose(n(paddle.diag(paddle.to_tensor([1., 2.]))),
                           np.diag([1., 2.]))
        assert np.allclose(n(paddle.tril(paddle.ones([3, 3]))),
                           np.tril(np.ones((3, 3))))

    def test_like_ops(self):
        x = paddle.ones([2, 2])
        assert n(paddle.zeros_like(x)).sum() == 0
        assert n(paddle.full_like(x, 3)).sum() == 12


class TestMath:
    def setup_method(self, _):
        self.a = np.random.RandomState(0).randn(3, 4).astype("float32")
        self.b = np.abs(np.random.RandomState(1).randn(3, 4)
                        ).astype("float32") + 0.5
        self.ta = paddle.to_tensor(self.a)
        self.tb = paddle.to_tensor(self.b)

    def test_binary(self):
        assert np.allclose(n(self.ta + self.tb), self.a + self.b)
        assert np.allclose(n(self.ta - self.tb), self.a - self.b)
        assert np.allclose(n(self.ta * self.tb), self.a * self.b)
        assert np.allclose(n(self.ta / self.tb), self.a / self.b, rtol=1e-5)
        assert np.allclose(n(paddle.maximum(self.ta, self.tb)),
                           np.maximum(self.a, self.b))
        assert np.allclose(n(paddle.pow(self.tb, 2.0)), self.b ** 2, rtol=1e-5)

    def test_unary(self):
        assert np.allclose(n(paddle.exp(self.ta)), np.exp(self.a), rtol=1e-5)
        assert np.allclose(n(paddle.log(self.tb)), np.log(self.b), rtol=1e-5)
        assert np.allclose(n(paddle.sqrt(self.tb)), np.sqrt(self.b), rtol=1e-5)
        assert np.allclose(n(paddle.tanh(self.ta)), np.tanh(self.a), rtol=1e-5)
        assert np.allclose(n(paddle.abs(self.ta)), np.abs(self.a))
        assert np.allclose(n(paddle.floor(self.ta)), np.floor(self.a))
        assert np.allclose(n(paddle.sign(self.ta)), np.sign(self.a))

    def test_reductions(self):
        assert np.allclose(n(paddle.sum(self.ta)), self.a.sum(), rtol=1e-5)
        assert np.allclose(n(paddle.mean(self.ta, axis=1)),
                           self.a.mean(1), rtol=1e-5)
        assert np.allclose(n(paddle.max(self.ta, axis=0)), self.a.max(0))
        assert np.allclose(n(paddle.prod(self.tb, axis=1, keepdim=True)),
                           self.b.prod(1, keepdims=True), rtol=1e-4)
        assert np.allclose(n(paddle.logsumexp(self.ta)),
                           np.log(np.exp(self.a).sum()), rtol=1e-5)

    def test_cumulative(self):
        assert np.allclose(n(paddle.cumsum(self.ta, axis=1)),
                           self.a.cumsum(1), rtol=1e-5)
        v, i = paddle.cummax(paddle.to_tensor([1., 3., 2., 5., 4.]))
        assert n(v).tolist() == [1., 3., 3., 5., 5.]
        assert n(i).tolist() == [0, 1, 1, 3, 3]

    def test_clip_lerp(self):
        assert np.allclose(n(paddle.clip(self.ta, -0.5, 0.5)),
                           np.clip(self.a, -0.5, 0.5))
        x = paddle.to_tensor([0.0, 1.0])
        y = paddle.to_tensor([10.0, 11.0])
        assert n(paddle.lerp(x, y, 0.5)).tolist() == [5.0, 6.0]

    def test_einsum(self):
        out = paddle.einsum("ij,kj->ik", self.ta, self.tb)
        assert np.allclose(n(out), self.a @ self.b.T, rtol=1e-4)


class TestManip:
    def setup_method(self, _):
        self.a = np.arange(24, dtype="float32").reshape(2, 3, 4)
        self.t = paddle.to_tensor(self.a)

    def test_reshape_transpose(self):
        assert n(paddle.reshape(self.t, [6, 4])).shape == (6, 4)
        assert n(paddle.transpose(self.t, [2, 0, 1])).shape == (4, 2, 3)
        assert n(paddle.flatten(self.t, 1)).shape == (2, 12)
        assert n(self.t.T).shape == (4, 3, 2)

    def test_concat_split_stack(self):
        c = paddle.concat([self.t, self.t], axis=1)
        assert n(c).shape == (2, 6, 4)
        parts = paddle.split(c, 2, axis=1)
        assert len(parts) == 2 and np.allclose(n(parts[0]), self.a)
        s = paddle.stack([self.t, self.t], axis=0)
        assert n(s).shape == (2, 2, 3, 4)
        parts = paddle.split(self.t, [1, -1], axis=1)
        assert n(parts[1]).shape == (2, 2, 4)

    def test_squeeze_unsqueeze_tile(self):
        u = paddle.unsqueeze(self.t, [0, 2])
        assert n(u).shape == (1, 2, 1, 3, 4)
        assert n(paddle.squeeze(u)).shape == (2, 3, 4)
        assert n(paddle.tile(paddle.ones([2]), [3])).shape == (6,)
        assert n(paddle.expand(paddle.ones([1, 2]), [3, 2])).shape == (3, 2)

    def test_gather_scatter(self):
        idx = paddle.to_tensor([0, 1, 1])
        g = paddle.gather(self.t, idx, axis=1)
        assert np.allclose(n(g), self.a[:, [0, 1, 1]])
        x = paddle.zeros([4, 2])
        upd = paddle.ones([2, 2])
        out = paddle.scatter(x, paddle.to_tensor([1, 3]), upd)
        assert n(out)[1].tolist() == [1, 1] and n(out)[0].tolist() == [0, 0]
        tk = paddle.take_along_axis(
            paddle.to_tensor([[1., 2., 3.]]), paddle.to_tensor([[2, 0]]), 1)
        assert n(tk).tolist() == [[3., 1.]]

    def test_sort_topk_search(self):
        x = paddle.to_tensor([3., 1., 2.])
        assert n(paddle.sort(x)).tolist() == [1., 2., 3.]
        assert n(paddle.argsort(x)).tolist() == [1, 2, 0]
        v, i = paddle.topk(x, 2)
        assert n(v).tolist() == [3., 2.] and n(i).tolist() == [0, 2]
        ss = paddle.searchsorted(paddle.to_tensor([1., 3., 5.]),
                                 paddle.to_tensor([2., 5.]))
        assert n(ss).tolist() == [1, 2]

    def test_masked_flip_roll(self):
        m = self.t > 11
        sel = paddle.masked_select(self.t, m)
        assert n(sel).tolist() == list(range(12, 24))
        mf = paddle.masked_fill(self.t, m, -1.0)
        assert n(mf).max() == 11
        assert np.allclose(n(paddle.flip(self.t, [0])), self.a[::-1])
        assert np.allclose(n(paddle.roll(self.t, 1, axis=0)),
                           np.roll(self.a, 1, axis=0))

    def test_unique_nonzero(self):
        x = paddle.to_tensor([3, 1, 3, 2, 1])
        u = paddle.unique(x)
        assert n(u).tolist() == [1, 2, 3]
        nz = paddle.nonzero(paddle.to_tensor([0, 5, 0, 7]))
        assert n(nz).reshape(-1).tolist() == [1, 3]

    def test_pad(self):
        p = paddle.nn.functional.pad(paddle.ones([1, 1, 2, 2]), [1, 1, 0, 0])
        assert n(p).shape == (1, 1, 2, 4)

    def test_getitem(self):
        assert np.allclose(n(self.t[0]), self.a[0])
        assert np.allclose(n(self.t[:, 1:3]), self.a[:, 1:3])
        assert np.allclose(n(self.t[..., -1]), self.a[..., -1])


class TestLinalg:
    def test_basic(self):
        a = np.random.RandomState(0).randn(3, 3).astype("float64")
        a = a @ a.T + 3 * np.eye(3)  # SPD
        t = paddle.to_tensor(a)
        assert np.allclose(n(paddle.linalg.inv(t)) @ a, np.eye(3), atol=1e-8)
        assert np.allclose(n(paddle.linalg.det(t)), np.linalg.det(a))
        l = paddle.linalg.cholesky(t)
        assert np.allclose(n(l) @ n(l).T, a, atol=1e-8)
        w = paddle.linalg.eigvalsh(t)
        assert np.allclose(np.sort(n(w)), np.sort(np.linalg.eigvalsh(a)))
        u, s, vt = paddle.linalg.svd(t)
        assert np.allclose(n(u) * n(s) @ n(vt), a, atol=1e-8)
        sol = paddle.linalg.solve(t, paddle.ones([3]))
        assert np.allclose(a @ n(sol), np.ones(3), atol=1e-8)

    def test_norm_matmul(self):
        a = np.random.RandomState(0).randn(4, 3).astype("float32")
        t = paddle.to_tensor(a)
        assert np.allclose(n(paddle.linalg.norm(t)),
                           np.linalg.norm(a), rtol=1e-5)
        assert np.allclose(
            n(paddle.matmul(t, t, transpose_x=True)), a.T @ a, rtol=1e-4)


class TestLogicStat:
    def test_compare(self):
        x = paddle.to_tensor([1, 2, 3])
        y = paddle.to_tensor([2, 2, 2])
        assert n(paddle.equal(x, y)).tolist() == [False, True, False]
        assert n(paddle.less_than(x, y)).tolist() == [True, False, False]
        assert bool(paddle.allclose(x.astype("float32"),
                                    x.astype("float32")))
        w = paddle.where(x > 2, x, y)
        assert n(w).tolist() == [2, 2, 3]

    def test_stats(self):
        a = np.random.RandomState(0).randn(5, 6).astype("float32")
        t = paddle.to_tensor(a)
        assert np.allclose(n(paddle.std(t)), a.std(ddof=1), rtol=1e-5)
        assert np.allclose(n(paddle.var(t, axis=0)),
                           a.var(0, ddof=1), rtol=1e-5)
        assert np.allclose(n(paddle.median(t)), np.median(a))
        assert n(paddle.bincount(paddle.to_tensor([0, 1, 1, 3]))).tolist() \
            == [1, 2, 0, 1]
        h = paddle.histogram(t, bins=4, min=-2, max=2)
        assert int(n(h).sum()) <= a.size

    def test_argmax(self):
        x = paddle.to_tensor([[1., 5., 3.], [9., 2., 4.]])
        assert n(paddle.argmax(x, axis=1)).tolist() == [1, 0]
        assert int(paddle.argmax(x)) == 3
        assert n(paddle.argmin(x, axis=0)).tolist() == [0, 1, 0]


class TestRandom:
    def test_seeded_determinism(self):
        paddle.seed(7)
        a = paddle.randn([4]).numpy()
        paddle.seed(7)
        b = paddle.randn([4]).numpy()
        assert np.allclose(a, b)

    def test_shapes_ranges(self):
        r = paddle.rand([100])
        assert n(r).min() >= 0 and n(r).max() < 1
        ri = paddle.randint(0, 5, [100])
        assert n(ri).min() >= 0 and n(ri).max() < 5
        perm = paddle.randperm(10)
        assert sorted(n(perm).tolist()) == list(range(10))
        b = paddle.bernoulli(paddle.full([1000], 0.3))
        assert 0.1 < n(b).mean() < 0.5


class TestDtype:
    def test_astype_cast(self):
        x = paddle.to_tensor([1.7, 2.3])
        assert x.astype("int32").dtype == np.int32
        assert paddle.cast(x, "float64").dtype == np.float64
        assert x.astype(paddle.bfloat16).dtype.name == "bfloat16"
