"""Continuous-batching serving engine (nlp/serving.py + paged_cache).

Pins the round-7 contracts:
- batched paged decode is TOKEN-EXACT vs the memoized sequential
  generate() under greedy, for GPT and Llama (GQA);
- seeded sampling stays inside the strategy's support (every emitted
  token is in the per-step top-k of the dense reference logits);
- pages are recycled across admission/eviction and the free list
  returns to its initial size (no leaks, no corruption across reuse);
- the steady state compiles NOTHING (trace counters frozen across a
  second wave of same-bucket requests);
- eos early-stop and back-pressure (more requests than slots/pages).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nlp.generation import generate
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
from paddle_tpu.nlp.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nlp.serving import ServingEngine


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    return m


@pytest.fixture(scope="module")
def llama_model():
    paddle.seed(0)
    # GQA: 4 query heads share 2 kv heads
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=256, hidden_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=128, max_position_embeddings=128))
    m.eval()
    return m


def _prompts(lens, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype(np.int32) for n in lens]


def _greedy_ref(model, prompts, new_tok):
    out = []
    for p in prompts:
        ids = generate(model, jnp.asarray(p)[None, :],
                       max_new_tokens=new_tok, temperature=0.0)
        out.append(np.asarray(ids._value)[0, len(p):].tolist())
    return out


class TestGreedyParity:
    def test_gpt_token_exact(self, gpt_model):
        # lengths straddle the 16-token page and the pow2 buckets
        prompts = _prompts((5, 12, 17, 30))
        refs = _greedy_ref(gpt_model, prompts, 10)
        eng = ServingEngine(gpt_model, max_slots=4, page_size=16,
                            max_seq_len=64, steps_per_dispatch=4)
        outs = eng.generate(prompts, max_new_tokens=10)
        assert outs == refs

    def test_llama_gqa_token_exact(self, llama_model):
        prompts = _prompts((6, 20), seed=1)
        refs = _greedy_ref(llama_model, prompts, 8)
        eng = ServingEngine(llama_model, max_slots=2, page_size=16,
                            max_seq_len=48, steps_per_dispatch=4)
        assert eng.generate(prompts, max_new_tokens=8) == refs

    def test_gpt_reduced_precision_caches_run(self, gpt_model):
        # bf16/int8 caches are throughput levers, not exactness
        # contracts — pin that they decode and stay near the fp32 path
        prompts = _prompts((5, 12))
        refs = _greedy_ref(gpt_model, prompts, 8)
        for dt in ("bfloat16", "int8"):
            eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                                max_seq_len=48, cache_dtype=dt)
            outs = eng.generate(prompts, max_new_tokens=8)
            agree = sum(a == b for r, o in zip(refs, outs)
                        for a, b in zip(r, o))
            total = sum(len(r) for r in refs)
            assert agree >= total * 0.75, (dt, refs, outs)


class TestSampling:
    def test_topk_tokens_in_reference_support(self, gpt_model):
        """Seeded top-k sampling: every emitted token must lie in the
        top-k of the dense model's logits for the exact same prefix —
        the distributional parity pin that survives rng-stream
        differences vs generate()."""
        k = 5
        prompt = _prompts((9,), seed=3)[0]
        eng = ServingEngine(gpt_model, max_slots=1, page_size=16,
                            max_seq_len=48, temperature=0.9, top_k=k,
                            seed=7)
        toks = eng.generate([prompt], max_new_tokens=6)[0]
        prefix = list(prompt)
        for t in toks:
            logits = gpt_model(paddle.to_tensor(
                np.asarray(prefix, np.int64)[None, :]))
            last = np.asarray(logits._value)[0, -1]
            topk = set(np.argsort(last)[-k:].tolist())
            assert t in topk, (t, sorted(topk))
            prefix.append(t)

    def test_greedy_is_temperature_zero(self, gpt_model):
        prompts = _prompts((7,))
        refs = _greedy_ref(gpt_model, prompts, 6)
        eng = ServingEngine(gpt_model, max_slots=1, page_size=16,
                            max_seq_len=48, temperature=0.0, top_k=3)
        assert eng.generate(prompts, max_new_tokens=6) == refs


class TestPagingAndScheduling:
    def test_page_recycling_and_backpressure(self, gpt_model):
        """More requests than slots AND a page pool too small to host
        them all at once: admission must back-pressure, finished
        sequences must return their pages, and every request must
        still decode token-exactly."""
        prompts = _prompts((5, 12, 17, 9, 21, 14), seed=5)
        refs = _greedy_ref(gpt_model, prompts, 8)
        # 2 slots, 7 usable pages: slot capacity is 2-3 pages/request
        eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                            max_seq_len=48, num_pages=8,
                            steps_per_dispatch=4)
        free0 = eng.free_page_count
        outs = eng.generate(prompts, max_new_tokens=8)
        assert outs == refs
        assert eng.free_page_count == free0, "page leak across recycle"

    def test_eos_early_stop(self, gpt_model):
        prompts = _prompts((5,))
        ref = _greedy_ref(gpt_model, prompts, 12)[0]
        eos = ref[2]
        first = ref.index(eos)  # greedy repeats: stop at FIRST hit
        eng = ServingEngine(gpt_model, max_slots=1, page_size=16,
                            max_seq_len=48)
        out = eng.generate(prompts, max_new_tokens=12,
                           eos_token_id=eos)[0]
        assert out == ref[:first + 1], \
            "must stop right after emitting eos"

    def test_non_pow2_page_size(self, gpt_model):
        """page_size=24 is a legal multiple of 8 but not a power of
        two: the prompt bucket must round up to whole pages (the
        write_prompt_kv block reshape) and still decode token-exactly."""
        prompts = _prompts((5, 30), seed=13)
        refs = _greedy_ref(gpt_model, prompts, 6)
        eng = ServingEngine(gpt_model, max_slots=2, page_size=24,
                            max_seq_len=72)
        assert eng.generate(prompts, max_new_tokens=6) == refs

    def test_submit_rejects_oversized(self, gpt_model):
        eng = ServingEngine(gpt_model, max_slots=1, page_size=16,
                            max_seq_len=32)
        with pytest.raises(ValueError, match="max_seq_len"):
            eng.submit(np.zeros(30, np.int32), max_new_tokens=10)


class TestZeroRecompile:
    def test_steady_state_compiles_nothing(self, gpt_model):
        eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                            max_seq_len=48, steps_per_dispatch=2)
        prompts = _prompts((5, 12))
        out1 = eng.generate(prompts, max_new_tokens=6)
        frozen = eng.compile_counts()
        assert frozen.get("decode") == 1
        # second wave: same buckets, new admissions/evictions — the
        # continuous-batching contract is ZERO new traces
        prompts2 = _prompts((6, 11, 13, 4), seed=9)
        eng.generate(prompts2, max_new_tokens=6)
        assert eng.compile_counts() == frozen
        # waves decoded something and parity held within the run
        assert eng.generate(prompts, max_new_tokens=6) == out1
        assert eng.compile_counts() == frozen

    def test_new_bucket_traces_prefill_only(self, gpt_model):
        eng = ServingEngine(gpt_model, max_slots=1, page_size=16,
                            max_seq_len=64, steps_per_dispatch=2)
        eng.generate(_prompts((5,)), max_new_tokens=4)     # bucket 16
        c = eng.compile_counts()
        eng.generate(_prompts((20,)), max_new_tokens=4)    # bucket 32
        c2 = eng.compile_counts()
        assert c2["decode"] == c["decode"], "decode must not retrace"
        assert c2.get("prefill_32") == 1


class TestPagedKernelRouting:
    def test_forced_flash_matches_reference(self):
        """use_flash=True routes the Pallas paged kernel (interpret
        mode on CPU) — greedy tokens must match the jnp reference
        path exactly (head_dim 64 so the gate accepts)."""
        paddle.seed(2)
        m = GPTForCausalLM(_resolve_config("gpt-tiny",
                                           num_attention_heads=1))
        m.eval()
        prompts = _prompts((5, 12), seed=11)
        ref_eng = ServingEngine(m, max_slots=2, page_size=16,
                                max_seq_len=48, use_flash=False)
        refs = ref_eng.generate(prompts, max_new_tokens=6)
        fl_eng = ServingEngine(m, max_slots=2, page_size=16,
                               max_seq_len=48, use_flash=True)
        assert fl_eng.use_flash, "gate should accept head_dim 64"
        assert fl_eng.generate(prompts, max_new_tokens=6) == refs

    def test_gate_rejects_unsupported_head_dim(self, gpt_model):
        # gpt-tiny head_dim=16: even a forced flash must fall back
        eng = ServingEngine(gpt_model, max_slots=1, page_size=16,
                            max_seq_len=48, use_flash=True)
        assert not eng.use_flash
