"""Fault-tolerance drills (VERDICT r2 next #8).

Drill 1: SIGKILL a Model.fit mid-training, resume from the rolling
per-epoch checkpoint, and require the resumed loss curve to continue the
uninterrupted golden run exactly (params + optimizer moments + LR
schedule + step counter all restored; per-step rng derives from the step
counter, so determinism carries across the kill).

Drill 2: elastic re-mesh — an 8-way ZeRO-sharded (orbax) checkpoint is
restored onto a 4-device mesh in a separate process and training
continues with the same losses.

ref parity: fleet elastic / paddle.distributed.fleet.utils.fs recovery
story; checkpoints via io/checkpoint.py CheckpointManager.
"""
import json
import os
import queue
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_FIT_SCRIPT = r"""
import sys, os, json, glob
sys.path.insert(0, __REPO__)
import _cpu_env
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.hapi.callbacks import Callback, ModelCheckpoint

mode, ckdir, out = sys.argv[1], sys.argv[2], sys.argv[3]
TOTAL = 10

paddle.seed(0)
net = paddle.nn.Sequential(paddle.nn.Linear(8, 32), paddle.nn.Tanh(),
                           paddle.nn.Linear(32, 4))
model = paddle.Model(net)
sched = paddle.optimizer.lr.StepDecay(0.05, step_size=3, gamma=0.5)
model.prepare(paddle.optimizer.AdamW(sched, parameters=net.parameters()),
              paddle.nn.CrossEntropyLoss())

rng = np.random.default_rng(0)
X = rng.standard_normal((16, 8)).astype('float32')
Y = rng.integers(0, 4, (16,)).astype('int64')
ds = paddle.io.TensorDataset([X, Y])

start = 0
if mode == 'resume':
    # an epoch checkpoint is "complete" iff both files landed (the kill
    # can land between the .pdparams and .pdopt writes)
    done = sorted(int(os.path.basename(p)[:-len('.pdparams')])
                  for p in glob.glob(os.path.join(ckdir, '*.pdparams'))
                  if os.path.exists(p[:-len('.pdparams')] + '.pdopt'))
    assert done, 'no complete checkpoint to resume from'
    start = done[-1] + 1
    model.load(os.path.join(ckdir, str(done[-1])))

losses = {}

class Rec(Callback):
    def on_epoch_end(self, epoch, logs=None):
        g = start + epoch  # global epoch number
        l = logs['loss']
        losses[g] = float(l[0] if isinstance(l, (list, tuple)) else l)
        print(f'EPOCH {g} {losses[g]}', flush=True)

class Saver(Callback):
    def on_epoch_end(self, epoch, logs=None):
        os.makedirs(ckdir, exist_ok=True)
        self.model.save(os.path.join(ckdir, str(start + epoch)))

class Pacer(Callback):
    # victim-only: stretch epochs to real-workload timescales so the
    # parent's SIGKILL lands mid-fit, not after a suspiciously fast finish
    def on_epoch_begin(self, epoch, logs=None):
        import time as _t
        _t.sleep(0.4)

cbs = [Rec()] + ([Saver(), Pacer()] if mode in ('victim',) else [])
model.fit(ds, epochs=TOTAL - start, batch_size=16, verbose=0, callbacks=cbs,
          shuffle=False)
with open(out, 'w') as f:
    json.dump(losses, f)
"""


def _run_fit(tmp, mode, timeout=240, kill_at=None):
    script = tmp / f"fit_{mode}.py"
    script.write_text(_FIT_SCRIPT.replace("__REPO__", repr(_REPO)))
    ckdir = str(tmp / "ck")
    out = str(tmp / f"losses_{mode}.json")
    proc = subprocess.Popen(
        [sys.executable, str(script), mode, ckdir, out],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=_REPO)
    killed = False
    t0 = time.time()
    lines = []
    # reader thread: a child that wedges BEFORE printing anything must
    # still hit the timeout (a bare `for line in proc.stdout` would block
    # the test forever — the exact wedge class this suite drills)
    q = queue.Queue()

    def _pump():
        for ln in proc.stdout:
            q.put(ln)
        q.put(None)
    th = threading.Thread(target=_pump, daemon=True)
    th.start()
    while True:
        try:
            line = q.get(timeout=max(0.1, timeout - (time.time() - t0)))
        except Exception:
            line = "__timeout__"
        if line == "__timeout__" or time.time() - t0 > timeout:
            proc.kill()
            raise TimeoutError("".join(lines[-20:]))
        if line is None:
            break
        lines.append(line)
        if kill_at is not None and line.startswith(f"EPOCH {kill_at} "):
            time.sleep(0.2)  # let the epoch's checkpoint land, then die
            proc.send_signal(signal.SIGKILL)
            killed = True
            break
    proc.wait(timeout=timeout)
    if not killed and proc.returncode != 0:
        raise RuntimeError("".join(lines[-30:]))
    return out, killed


def test_kill_mid_fit_resume_loss_continuity(tmp_path):
    golden_out, _ = _run_fit(tmp_path, "golden")
    golden = {int(k): v for k, v in json.load(open(golden_out)).items()}
    assert len(golden) == 10

    _, killed = _run_fit(tmp_path, "victim", kill_at=4)
    assert killed, "victim was supposed to be SIGKILLed mid-fit"
    assert not os.path.exists(str(tmp_path / "losses_victim.json")), \
        "victim survived to the end — the kill happened too late"

    resume_out, _ = _run_fit(tmp_path, "resume")
    resumed = {int(k): v for k, v in json.load(open(resume_out)).items()}
    # resumed run must continue the golden curve from the checkpoint on:
    # same params, moments, LR-schedule position and step-derived rng
    assert min(resumed) == 5, resumed
    for e in sorted(resumed):
        np.testing.assert_allclose(
            resumed[e], golden[e], rtol=1e-5, atol=1e-7,
            err_msg=f"loss diverged at epoch {e}: resume broke exactness")


_ZERO_SCRIPT = r"""
import sys, os, json
sys.path.insert(0, __REPO__)
import _cpu_env
import jax, jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
import paddle_tpu as paddle
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.hapi.engine import Engine
from paddle_tpu.io.checkpoint import CheckpointManager

mode, ckdir, out = sys.argv[1], sys.argv[2], sys.argv[3]
ndev = len(jax.devices())

def build():
    paddle.seed(7)
    net = paddle.nn.Sequential(paddle.nn.Linear(16, 64), paddle.nn.ReLU(),
                               paddle.nn.Linear(64, 8))
    opt = paddle.optimizer.AdamW(1e-2, parameters=net.parameters())
    mesh = Mesh(np.array(jax.devices()), ('dp',))
    net, opt, _ = group_sharded_parallel(net, opt, level='os_g', mesh=mesh)
    eng = Engine(net, loss=paddle.nn.CrossEntropyLoss(), optimizer=opt,
                 mesh=mesh)
    return eng

def data(step):
    rng = np.random.default_rng(100 + step)
    x = rng.standard_normal((16, 16)).astype('float32')
    y = rng.integers(0, 8, (16,)).astype('int64')
    return jnp.asarray(x), jnp.asarray(y)

eng = build()
mgr = CheckpointManager(ckdir, sharded=True)
losses = []
if mode == 'save':
    for s in range(3):
        x, y = data(s)
        loss, _ = eng.train_batch([x], [y])
        losses.append(float(loss))
    mgr.save(3, {'params': eng._params, 'opt': eng._opt_state,
                 'step': eng._step})
    mgr.wait()
    for s in range(3, 5):   # golden continuation on THIS mesh
        x, y = data(s)
        loss, _ = eng.train_batch([x], [y])
        losses.append(float(loss))
else:  # restore onto the current (different-size) mesh
    x0, y0 = data(0)
    loss0, _ = eng.train_batch([x0], [y0])  # materialize opt state/shardings
    target = {'params': eng._params, 'opt': eng._opt_state, 'step': 0}
    st = mgr.restore(target=target)
    eng._params = st['params']
    eng._opt_state = st['opt']
    eng._step = st['step']
    eng._opt_step = st['step']  # update counter: fused path keeps ==step
    eng.network.load_raw_state(eng._params, eng._buffers)
    eng._train_fn = None  # rebuild for the restored placements
    for s in range(3, 5):
        x, y = data(s)
        loss, _ = eng.train_batch([x], [y])
        losses.append(float(loss))
    # proof of re-sharding: a moment leaf lives on this smaller mesh
    leaf = jax.tree_util.tree_leaves(eng._opt_state['m'])[0]
    assert len(leaf.sharding.mesh.devices.flatten()) == ndev, \
        (leaf.sharding, ndev)
with open(out, 'w') as f:
    json.dump({'ndev': ndev, 'losses': losses}, f)
"""


def _run_zero(tmp, mode, ndev, timeout=300):
    script = tmp / f"zero_{mode}_{ndev}.py"
    script.write_text(_ZERO_SCRIPT.replace("__REPO__", repr(_REPO)))
    out = str(tmp / f"zero_{mode}_{ndev}.json")
    env = dict(os.environ)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={ndev}"
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, str(script), mode, str(tmp / "zck"), out],
        capture_output=True, text=True, timeout=timeout, cwd=_REPO, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    return json.load(open(out))


def test_elastic_remesh_zero_8_to_4(tmp_path):
    """8-way ZeRO checkpoint restored onto a 4-device mesh: orbax restores
    each array straight onto the new NamedSharding (per-shard reads, no
    full-host gather) and the continued loss curve matches the 8-way one
    (dp mean-loss math is mesh-size invariant over the same global
    batch)."""
    saved = _run_zero(tmp_path, "save", 8)
    restored = _run_zero(tmp_path, "restore", 4)
    assert restored["ndev"] == 4
    np.testing.assert_allclose(restored["losses"], saved["losses"][3:],
                               rtol=1e-4, atol=1e-5)
