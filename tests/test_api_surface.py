"""SURVEY §2 API-surface probe: every name the inventory claims must
resolve (r2's ColorJitter was listed but absent — an AttributeError no
test caught; this file makes that class of gap impossible to miss).

Existence-only by design: numerics live in the per-family test files.
"""
import importlib

import pytest

import paddle_tpu as paddle


def _resolve(path):
    obj = paddle
    for part in path.split("."):
        obj = getattr(obj, part)
    return obj


TENSOR_OPS = (
    # §2.1 creation
    "to_tensor zeros ones full arange linspace eye empty zeros_like "
    "ones_like full_like empty_like rand randn randint normal uniform "
    # §2.1 math
    "add subtract multiply divide matmul pow sqrt rsqrt exp log abs floor "
    "ceil round clip sum mean max min prod cumsum argmax argmin maximum "
    "minimum sign square reciprocal remainder mod floor_divide log2 log10 "
    "log1p expm1 sin cos tan asin acos atan atan2 sinh cosh tanh erf "
    "logsumexp isnan isinf isfinite nanmean nansum trunc frac lerp addmm "
    "outer inner dot cross trace diag kron logcumsumexp amax amin "
    # §2.1 logic/compare
    "equal not_equal less_than less_equal greater_than greater_equal "
    "logical_and logical_or logical_not logical_xor allclose isclose "
    "equal_all where "
    # §2.1 manipulation
    "reshape transpose concat stack split chunk squeeze unsqueeze flatten "
    "tile expand broadcast_to gather gather_nd scatter scatter_nd_add "
    "index_select index_put masked_select masked_fill flip roll unbind "
    "repeat_interleave take_along_axis put_along_axis as_strided slice "
    "strided_slice unique sort argsort topk searchsorted bucketize nonzero "
    "tril triu diagflat rot90 moveaxis swapaxes unfold "
    # §2.1 stats/random + misc
    "std var median quantile kthvalue mode histogram bincount multinomial "
    "bernoulli poisson randperm seed einsum cast "
    # §2.12 long tail
    "nextafter xlogy signbit isreal vdot renorm combinations "
    "cartesian_prod cdist trapz unflatten index_fill slice_scatter "
    "column_stack row_stack hsplit vsplit dsplit tensor_split lu_unpack "
    "matrix_exp"
).split()

LINALG = ("norm inv det slogdet svd qr eig eigh eigvals eigvalsh cholesky "
          "cholesky_solve lstsq lu matrix_power matrix_rank pinv solve "
          "triangular_solve cond corrcoef cov householder_product "
          "multi_dot").split()

NN = ("Layer Linear Conv1D Conv2D Conv3D Conv2DTranspose Embedding "
      "BatchNorm1D BatchNorm2D BatchNorm3D LayerNorm GroupNorm RMSNorm "
      "SyncBatchNorm Dropout Dropout2D AlphaDropout MaxPool1D MaxPool2D "
      "AvgPool1D AvgPool2D AdaptiveAvgPool2D AdaptiveMaxPool2D "
      "FractionalMaxPool2D Upsample Pad1D Pad2D Pad3D PixelShuffle Flatten "
      "Unfold Bilinear Softmax2D LogSigmoid AdaptiveLogSoftmaxWithLoss "
      "ReLU ReLU6 GELU Silu Swish Sigmoid Tanh Softmax LogSoftmax LeakyReLU "
      "PReLU ELU SELU CELU GLU Hardswish Hardsigmoid Hardtanh Mish "
      "Softplus Softshrink Softsign Tanhshrink ThresholdedReLU Maxout "
      "CrossEntropyLoss MSELoss L1Loss SmoothL1Loss NLLLoss BCELoss "
      "BCEWithLogitsLoss KLDivLoss CosineEmbeddingLoss MarginRankingLoss "
      "HingeEmbeddingLoss CTCLoss TripletMarginLoss PoissonNLLLoss "
      "HuberLoss GaussianNLLLoss MultiLabelSoftMarginLoss SoftMarginLoss "
      "MultiMarginLoss TripletMarginWithDistanceLoss MultiHeadAttention "
      "TransformerEncoder TransformerEncoderLayer TransformerDecoder "
      "TransformerDecoderLayer Transformer SimpleRNN LSTM GRU LSTMCell "
      "GRUCell SimpleRNNCell Sequential").split()

NN_FUNCTIONAL = ("relu gelu silu sigmoid tanh softmax log_softmax "
                 "scaled_dot_product_attention one_hot cosine_similarity "
                 "normalize pairwise_distance pixel_shuffle grid_sample "
                 "affine_grid conv2d linear embedding dropout layer_norm "
                 "batch_norm max_pool2d avg_pool2d interpolate pad "
                 "cross_entropy mse_loss zeropad2d max_unpool2d").split()

OPTIMIZER = ("SGD Momentum Adam AdamW Adamax Adagrad Adadelta RMSProp Lamb "
             "Rprop NAdam RAdam LBFGS").split()

LR = ("NoamDecay ExponentialDecay NaturalExpDecay InverseTimeDecay "
      "PolynomialDecay LinearWarmup PiecewiseDecay CosineAnnealingDecay "
      "StepDecay MultiStepDecay LambdaDecay ReduceOnPlateau OneCycleLR "
      "CyclicLR CosineAnnealingWarmRestarts LinearLR LRScheduler").split()

DISTRIBUTED = ("init_parallel_env get_rank get_world_size all_reduce "
               "all_gather reduce_scatter broadcast scatter reduce "
               "alltoall alltoall_single send recv barrier new_group "
               "shard_tensor shard_layer launch spawn DataParallel "
               "quantized_all_reduce").split()

DISTRIBUTION = ("Normal Uniform Beta Dirichlet Gamma Exponential Laplace "
                "LogNormal Gumbel Cauchy StudentT Bernoulli Categorical "
                "Multinomial Geometric Poisson Binomial Independent "
                "TransformedDistribution kl_divergence register_kl").split()

VISION_MODELS = ("LeNet resnet18 resnet34 resnet50 resnet101 resnet152 "
                 "vgg16 vgg19 mobilenet_v1 mobilenet_v2 mobilenet_v3_small "
                 "mobilenet_v3_large googlenet inception_v3 densenet121 "
                 "shufflenet_v2_x0_25 squeezenet1_0 alexnet "
                 "wide_resnet50_2 resnext50_32x4d SpaceToDepthStem").split()

VISION_TRANSFORMS = ("Compose Resize RandomCrop CenterCrop "
                     "RandomHorizontalFlip RandomVerticalFlip Normalize "
                     "ToTensor ColorJitter RandomResizedCrop Pad "
                     "BrightnessTransform ContrastTransform "
                     "SaturationTransform HueTransform Grayscale "
                     "RandomRotation RandomErasing RandomAffine "
                     "RandomPerspective").split()

IO = ("Dataset IterableDataset TensorDataset ConcatDataset Subset "
      "random_split Sampler SequenceSampler RandomSampler "
      "WeightedRandomSampler BatchSampler DistributedBatchSampler "
      "DataLoader").split()

GEOMETRIC = ("segment_sum segment_mean segment_max segment_min send_u_recv "
             "send_ue_recv send_uv").split()

FFT = ("fft ifft rfft irfft hfft ihfft fft2 ifft2 fftn ifftn fftfreq "
       "rfftfreq fftshift ifftshift").split()

TOP = ("Model summary flops save load grad no_grad seed Tensor "
       "to_tensor einsum iinfo finfo").split()

NLP = ("GPTConfig GPTModel GPTForCausalLM GPTPretrainingCriterion "
       "BertConfig BertModel BertForPretraining "
       "BertForSequenceClassification ErnieConfig ErnieModel "
       "ErnieForPretraining LlamaConfig LlamaModel LlamaForCausalLM "
       "LlamaPretrainingCriterion BertTokenizer GPTTokenizer").split()


@pytest.mark.parametrize("name", TENSOR_OPS)
def test_tensor_op_exists(name):
    assert _resolve(name) is not None


@pytest.mark.parametrize("name", LINALG)
def test_linalg_exists(name):
    assert getattr(paddle.linalg, name) is not None


@pytest.mark.parametrize("name", NN)
def test_nn_exists(name):
    assert getattr(paddle.nn, name) is not None


@pytest.mark.parametrize("name", NN_FUNCTIONAL)
def test_nn_functional_exists(name):
    assert getattr(paddle.nn.functional, name) is not None


@pytest.mark.parametrize("name", OPTIMIZER)
def test_optimizer_exists(name):
    assert getattr(paddle.optimizer, name) is not None


@pytest.mark.parametrize("name", LR)
def test_lr_exists(name):
    assert getattr(paddle.optimizer.lr, name) is not None


@pytest.mark.parametrize("name", DISTRIBUTED)
def test_distributed_exists(name):
    assert getattr(paddle.distributed, name) is not None


@pytest.mark.parametrize("name", DISTRIBUTION)
def test_distribution_exists(name):
    assert getattr(paddle.distribution, name) is not None


@pytest.mark.parametrize("name", VISION_MODELS)
def test_vision_model_exists(name):
    from paddle_tpu.vision import models
    assert getattr(models, name) is not None


@pytest.mark.parametrize("name", VISION_TRANSFORMS)
def test_vision_transform_exists(name):
    from paddle_tpu.vision import transforms
    assert getattr(transforms, name) is not None


@pytest.mark.parametrize("name", IO)
def test_io_exists(name):
    from paddle_tpu import io
    assert getattr(io, name) is not None


@pytest.mark.parametrize("name", NLP)
def test_nlp_exists(name):
    from paddle_tpu import nlp
    assert getattr(nlp, name) is not None


@pytest.mark.parametrize("name", GEOMETRIC)
def test_geometric_exists(name):
    assert getattr(paddle.geometric, name) is not None


@pytest.mark.parametrize("name", FFT)
def test_fft_exists(name):
    assert getattr(paddle.fft, name) is not None


@pytest.mark.parametrize("name", TOP)
def test_top_level_exists(name):
    assert _resolve(name) is not None


def test_amp_jit_static_namespaces():
    assert paddle.amp.auto_cast and paddle.amp.GradScaler
    assert paddle.amp.decorate
    assert paddle.jit.to_static and paddle.jit.save and paddle.jit.load
    assert paddle.static.InputSpec
    assert paddle.sparse is not None and paddle.audio is not None
    assert paddle.signal.stft and paddle.signal.istft
    from paddle_tpu.vision import ops as vops
    for n in ("nms", "box_iou", "roi_align", "roi_pool", "box_coder",
              "yolo_box", "deform_conv2d", "distribute_fpn_proposals"):
        assert getattr(vops, n) is not None
    from paddle_tpu import metric
    for n in ("Accuracy", "Precision", "Recall", "Auc"):
        assert getattr(metric, n) is not None
    from paddle_tpu.hapi import callbacks
    for n in ("ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
              "LRScheduler", "ReduceLROnPlateau"):
        assert getattr(callbacks, n) is not None


def test_reference_top_level_mode_and_legacy_apis():
    """Round-3 sweep: names every ported reference script touches."""
    import pytest
    assert paddle.disable_static() is None  # dygraph no-op
    with pytest.raises(NotImplementedError, match="to_static"):
        paddle.enable_static()
    assert paddle.is_compiled_with_xpu() is False
    assert paddle.is_compiled_with_rocm() is False
    assert paddle.callbacks.ModelCheckpoint is not None
    assert paddle.DataParallel is not None
    with pytest.raises(NotImplementedError, match="jit.save"):
        paddle.inference.Config("model")
    # legacy reader decorator
    b = paddle.batch(lambda: iter(range(7)), batch_size=3)
    got = list(b())
    assert got == [[0, 1, 2], [3, 4, 5], [6]]
    b2 = paddle.batch(lambda: iter(range(7)), batch_size=3, drop_last=True)
    assert list(b2()) == [[0, 1, 2], [3, 4, 5]]


def test_batch_rejects_nonpositive_size():
    import pytest
    with pytest.raises(ValueError, match="positive"):
        paddle.batch(lambda: iter([]), batch_size=0)
