"""Device-memory ledger plane (HBM ledger round;
observability/memledger.py).

Pins the round's contracts (docs/observability.md "Device memory"):

- two attribution channels: ``track``/``track_bytes`` tokens for
  owner-managed buffers (idempotent ``release``), ``set_level``
  absolute levels for recomputed inventories; unknown tags fold into
  ``other`` LABELED with the tag — a misspelled seam stays visible;
- conservation: typed segments + the ``unattributed_bytes`` residual
  equal ground truth within 1% across a full serve wave — prefill,
  prefix-cache hits, speculative decode, and a fleet failover — with
  compile counts frozen (accounting is host-side dict arithmetic);
- the residual alarm trips on a MiB-scale untracked allocation and
  stays quiet on noise under the ``max(1 MiB, 0.5*baseline)`` slack;
- headroom forecasting: high-watermark + EWMA growth +
  ``seconds_to_exhaustion``; ``would_fit`` is None when
  capacity-blind, and admission is advisory-by-default /
  typed-rejection in ``PADDLE_TPU_MEM_ADMISSION=hard`` mode;
- ``PrefixIndex.audit()`` cross-checks refcounts against the live
  page table and the ledger surfaces problems without raising;
- a never-armed engine creates NO ledger and registers NO ``mem_*``
  series (the spec-decode dormancy contract);
- ``/memory`` renders the armed segment tree live (and a stub when
  unarmed), self-timed in ``exporter_scrape_seconds``;
- the sentinel's gauge-kind ``mem_used_ratio`` signal trips on a
  used-ratio step out of the learned band and stays quiet on flat;
- the router delta-folds heartbeat digests into ``fleet_mem_*``
  (restart-reset-safe), publishes the fleet-max residual, rolls up
  health()["mem"], and scores the ``placement.mem_headroom`` term
  (weight 0 = byte-identical placement); fleet_top renders
  MEM%/HEADROOM off the rollup;
- tools/mem_diff.py gates per-segment drift in BOTH directions and
  fails vacuous comparisons;
- the optimizer seam level-sets ``optimizer_state``/``grads`` into
  the active ledger after ``step()``.
"""
import importlib
import json
import os
import sys
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
from paddle_tpu.nlp.serving import ServingEngine
from paddle_tpu.observability import memledger
from paddle_tpu.observability.history import HistoryStore
from paddle_tpu.observability.memledger import (MemoryAdmissionError,
                                               MemoryLedger, nbytes_of)
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.sentinel import (AnomalySentinel,
                                               default_signals)
from paddle_tpu.resilience import faults
from paddle_tpu.serving_fleet import FleetRouter, InprocReplica

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

T0 = 1_700_000_000.0


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    return m


def _prompts(lens, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype(np.int32) for n in lens]


def _shared_wave(seed=0):
    """Four requests over two distinct prompts: the repeats are
    guaranteed prefix-cache hits once the firsts registered."""
    base = _prompts((24, 20), seed=seed)
    return [base[0], base[1], base[0], base[1]]


def _armed(model, **kw):
    kw.setdefault("mem_ledger", True)
    kw.setdefault("mem_capacity_bytes", 1 << 30)
    kw.setdefault("steps_per_dispatch", 4)
    return ServingEngine(model, max_slots=2, page_size=16,
                         max_seq_len=64, **kw)


# -- ledger core -----------------------------------------------------------


class TestLedgerCore:
    def test_track_release_roundtrip_idempotent(self):
        led = MemoryLedger(ground_truth_fn=lambda: (0, None))
        try:
            t = led.track_bytes("kv_pages", 1000, label="dtype=f32")
            t2 = led.track_bytes("weights", 500)
            assert led.attributed_bytes() == 1500
            assert led.release(t) == 1000
            # releasing a dead token is a no-op, never a crash
            assert led.release(t) == 0
            assert led.attributed_bytes() == 500
            s = led.stats()
            assert s["tracked_allocs"] == 2
            assert s["released_allocs"] == 1
            assert led.release(t2) == 500
        finally:
            led.close()

    def test_set_level_overwrites_and_clears(self):
        led = MemoryLedger(ground_truth_fn=lambda: (0, None))
        try:
            led.set_level("prefix_sidecar", 4096)
            led.set_level("prefix_sidecar", 8192)   # absolute, not +=
            assert led.segments()["prefix_sidecar"] == 8192
            led.set_level("prefix_sidecar", 0)      # 0 clears the row
            assert "prefix_sidecar" not in led.segments()
        finally:
            led.close()

    def test_unknown_tag_folds_to_other_with_label(self):
        led = MemoryLedger(ground_truth_fn=lambda: (0, None))
        try:
            led.track_bytes("kv_pgaes", 777)        # the typo'd seam
            tree = led.segment_tree()
            assert led.segments()["other"] == 777
            # ...but the tag survives as a label, so the misspelling
            # is visible in the tree, never silently absorbed
            assert "kv_pgaes" in tree["other"]["labels"]
        finally:
            led.close()

    def test_nbytes_of_walks_and_dedups(self):
        a = np.zeros((8, 8), np.float32)            # 256 B
        b = np.zeros(16, np.int8)                   # 16 B
        assert nbytes_of(a) == 256
        assert nbytes_of({"x": a, "y": [b, (b,)]}) == 272, \
            "the same buffer reachable twice must count once"

    def test_conservation_against_injected_ground_truth(self):
        gt = {"v": 0}
        led = MemoryLedger(ground_truth_fn=lambda: (gt["v"], None))
        try:
            led.track_bytes("weights", 1000)
            gt["v"] = 1004
            led.sweep(force=True)
            c = led.conservation(tolerance=0.01)
            assert c["ok"] and c["unattributed_bytes"] == 4
            # under-attribution lands in the residual, VISIBLY — the
            # identity still holds (that is what the residual is for)
            gt["v"] = 1100
            led.sweep(force=True)
            c = led.conservation(tolerance=0.01)
            assert c["ok"] and c["unattributed_bytes"] == 100
            # OVER-attribution — a seam counting bytes the device no
            # longer holds — is the bug class that breaks the books
            gt["v"] = 800
            led.sweep(force=True)
            assert not led.conservation(tolerance=0.01)["ok"]
        finally:
            led.close()

    def test_residual_alarm_slack_floor_then_trip(self):
        gt = {"v": 1000}
        led = MemoryLedger(ground_truth_fn=lambda: (gt["v"], None))
        try:
            led.mark_baseline()
            # sub-floor growth (well under 1 MiB) is noise, not a leak
            gt["v"] += 700
            led.sweep(force=True)
            assert not led.residual_alarm
            # a MiB-scale untracked allocation is the leak signature
            gt["v"] += 2 << 20
            led.sweep(force=True)
            assert led.residual_alarm
        finally:
            led.close()

    def test_would_fit_none_when_capacity_blind(self):
        led = MemoryLedger(ground_truth_fn=lambda: (0, None))
        try:
            assert led.would_fit(1 << 20) is None
            # capacity-blind admission_check must not reject
            assert led.admission_check(1 << 20) is not False
        finally:
            led.close()

    def test_admission_check_counts_and_verdicts(self):
        reg = MetricsRegistry()
        led = MemoryLedger(registry=reg, capacity_bytes=10_000,
                           ground_truth_fn=lambda: (0, None))
        try:
            led.track_bytes("kv_pages", 9_000)
            assert led.would_fit(500) is True
            assert led.admission_check(500) is True
            assert led.would_fit(5_000) is False
            assert led.admission_check(5_000) is False
            s = led.stats()
            assert s["admission_checks"] == 2
            assert s["admission_rejections"] == 1
            assert int(reg.get(
                "engine_mem_admission_rejections_total").value) == 1
        finally:
            led.close()

    def test_growth_forecast_and_seconds_to_exhaustion(self):
        gt = {"v": 0}
        led = MemoryLedger(capacity_bytes=10_000_000,
                           ground_truth_fn=lambda: (gt["v"], None))
        try:
            for i in range(6):
                gt["v"] = 1_000_000 * (i + 1)   # +1 MB per second
                led.sweep(force=True, now=T0 + i)
            dg = led.digest(sweep=False)
            assert dg["growth_bytes_per_s"] == pytest.approx(
                1_000_000, rel=0.5)
            tte = led.seconds_to_exhaustion()
            assert tte is not None and 1.0 < tte < 30.0
            assert dg["high_watermark_bytes"] == 6_000_000
        finally:
            led.close()

    def test_snapshot_save_load_and_torn_tail(self, tmp_path):
        led = MemoryLedger(capacity_bytes=1 << 20,
                           ground_truth_fn=lambda: (2048, None))
        p = str(tmp_path / "mem.json")
        try:
            led.track_bytes("kv_pages", 2048, label="dtype=f32")
            led.save(p)
        finally:
            led.close()
        doc = memledger.load_snapshot(p)
        assert doc["memledger"] == 1
        assert doc["digest"]["segments"]["kv_pages"] == 2048
        raw = open(p, "rb").read()
        for cut in (0, 1, len(raw) // 2, len(raw) - 1):
            with open(p, "wb") as f:
                f.write(raw[:cut])
            assert memledger.load_snapshot(p) == {}, \
                "a torn snapshot must read as empty, never raise"

    def test_active_ledger_registry_lifecycle(self):
        assert memledger.active_ledger() is None
        assert memledger.current_memory() is None
        led = MemoryLedger(name="t-active",
                           ground_truth_fn=lambda: (0, None))
        try:
            led.track_bytes("weights", 64)
            assert memledger.active_ledger() is led
            rep = memledger.current_memory()
            assert rep is not None and rep["name"] == "t-active"
            assert rep["tree"]["weights"]["bytes"] == 64
        finally:
            led.close()
        assert memledger.active_ledger() is None
        assert memledger.current_memory() is None


# -- prefix refcount audit -------------------------------------------------


class TestPrefixRefcountAudit:
    def test_prefix_refcount_audit(self, gpt_model):
        """Clean engine audits clean; a corrupted refcount (the bug
        class: a COW splice that forgot its pin) is DETECTED, counted,
        and never raises out of the sweep."""
        eng = _armed(gpt_model)
        try:
            eng.warmup(buckets=[24, 20], decode=True)
            eng.generate(_shared_wave(), max_new_tokens=8)
            assert eng.prefix.stats()["hits"] > 0
            assert eng._mem_audit() == []
            # corrupt a refcount behind the index's back: a phantom
            # pin on an owned page — the page that would never return
            # to the free list
            page = next(iter(eng.prefix._owners))
            eng.prefix._rc[page] = eng.prefix._rc.get(page, 0) + 1
            problems = eng._mem_audit()
            assert problems and any(str(page) in p for p in problems)
            # the sweep surfaces it as a counter + bounded note list,
            # never an exception
            eng.ledger.sweep(force=True)
            assert eng.ledger.stats()["audit_failures"] >= 1
            assert eng.ledger.audit_problems
            del eng.prefix._rc[page]
            assert eng._mem_audit() == []
        finally:
            eng.close()


# -- engine integration ----------------------------------------------------


class TestEngineIntegration:
    def test_full_wave_conservation_frozen_compiles(self, gpt_model):
        """The acceptance drill: prefill + prefix hits + speculative
        decode through a ledger-armed engine — conservation within
        1%, every seam's segment populated, compile counts frozen."""
        eng = _armed(gpt_model, spec_decode=True, steps_per_dispatch=1)
        try:
            eng.warmup(buckets=[24, 20], decode=True)
            frozen = eng.compile_counts()
            outs = eng.generate(_shared_wave(), max_new_tokens=8)
            assert len(outs) == 4
            assert eng.compile_counts() == frozen, \
                "memory accounting must never touch the trace plane"
            assert eng.tracer.unexpected_retraces() == 0
            c = eng.ledger.conservation(tolerance=0.01)
            assert c["ok"], f"conservation broken: {c}"
            segs = eng.ledger.segments()
            assert segs["kv_pages"] > 0 and segs["weights"] > 0
            assert segs["prefix_sidecar"] > 0
            tree = eng.ledger.segment_tree()
            assert any("dtype=" in lb
                       for lb in tree["kv_pages"]["labels"])
            s = eng.ledger.stats()
            assert s["admission_checks"] >= 4
            h = eng.health()
            assert h["mem"]["attributed_bytes"] == segs_total(segs)
            assert h["mem"]["residual_alarm"] is False
        finally:
            eng.close()

    def test_dormant_engine_has_no_ledger_no_series(self, gpt_model):
        eng = ServingEngine(gpt_model, max_slots=1, page_size=16,
                            max_seq_len=64)
        try:
            assert eng.ledger is None
            assert eng.registry.get("engine_mem_attributed_bytes") \
                is None
            assert eng.registry.get(
                "engine_mem_admission_checks_total") is None
            assert "mem" not in eng.health()
        finally:
            eng.close()

    def test_hard_admission_rejects_typed(self, gpt_model):
        eng = _armed(gpt_model, mem_admission="hard",
                     mem_capacity_bytes=1)
        try:
            with pytest.raises(MemoryAdmissionError) as ei:
                eng.submit(_prompts((24,))[0], max_new_tokens=4)
            assert ei.value.need_bytes > 0
            assert ei.value.headroom_bytes == 0
            assert eng.ledger.stats()["admission_rejections"] >= 1
        finally:
            eng.close()

    def test_advisory_mode_counts_and_serves(self, gpt_model):
        # same impossible budget, default mode: the wave completes,
        # the rejections land in the counter — advisory means ADVICE
        eng = _armed(gpt_model, mem_capacity_bytes=1)
        try:
            eng.warmup(buckets=[24, 20], decode=True)
            outs = eng.generate(_shared_wave(), max_new_tokens=4)
            assert len(outs) == 4 and all(len(t) for t in outs)
            assert eng.ledger.stats()["admission_rejections"] >= 4
        finally:
            eng.close()

    def test_bad_admission_mode_rejected_loudly(self, gpt_model):
        with pytest.raises(ValueError):
            _armed(gpt_model, mem_admission="advisry")

    def test_memory_endpoint_armed_stub_and_catalogue(self, gpt_model):
        eng = _armed(gpt_model)
        try:
            eng.warmup(buckets=[24], decode=True)
            eng.generate(_prompts((24,)), max_new_tokens=4)
            ex = eng.serve_metrics(port=0)
            base = f"http://127.0.0.1:{ex.port}"
            with urllib.request.urlopen(base + "/memory?window=30",
                                        timeout=10) as r:
                doc = json.loads(r.read().decode())
            assert doc["armed"] is True
            assert doc["tree"]["kv_pages"]["bytes"] > 0
            assert doc["conservation"]["ok"]
            # the 404 catalogue advertises the route
            try:
                urllib.request.urlopen(base + "/nope", timeout=10)
            except urllib.error.HTTPError as e:
                lost = json.loads(e.read().decode())
            assert "/memory" in lost["endpoints"]
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as r:
                prom = r.read().decode()
            assert "engine_mem_attributed_bytes" in prom
            assert 'exporter_scrape_seconds' in prom \
                and 'route="/memory"' in prom
        finally:
            eng.close()
        # unarmed: the route stays probeable and answers a stub
        eng2 = ServingEngine(gpt_model, max_slots=1, page_size=16,
                             max_seq_len=64)
        try:
            ex2 = eng2.serve_metrics(port=0)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{ex2.port}/memory",
                    timeout=10) as r:
                stub = json.loads(r.read().decode())
            assert stub["armed"] is False and "note" in stub
        finally:
            eng2.close()


def segs_total(segs):
    return sum(int(v) for v in segs.values())


# -- optimizer seam --------------------------------------------------------


class TestOptimizerSeam:
    def test_step_levels_optimizer_state_and_grads(self):
        led = MemoryLedger(name="t-opt",
                           ground_truth_fn=lambda: (0, None))
        try:
            paddle.seed(0)
            layer = paddle.nn.Linear(4, 4)
            layer.train()
            opt = paddle.optimizer.Momentum(
                learning_rate=0.1, momentum=0.9,
                parameters=layer.parameters())
            x = paddle.to_tensor(
                np.random.default_rng(0).standard_normal(
                    (2, 4)).astype(np.float32))
            loss = layer(x).sum()
            loss.backward()
            opt.step()
            segs = led.segments()
            assert segs.get("optimizer_state", 0) > 0, \
                "momentum slots must land in the segment tree"
            assert segs.get("grads", 0) > 0
            lbl = led.segment_tree()["optimizer_state"]["labels"]
            assert any("Momentum" in k for k in lbl)
        finally:
            led.close()


# -- sentinel gauge signal -------------------------------------------------


class TestSentinelMemSignal:
    def _sig(self):
        sig = [s for s in default_signals()
               if s["name"] == "mem_used_ratio"][0]
        return dict(sig, window_s=2.0)

    def test_ratio_step_trips_flat_does_not(self):
        # flat-then-step: a leak pushing used-ratio out of the
        # learned band must fire...
        reg = MetricsRegistry()
        g = reg.gauge("engine_mem_hbm_used_ratio")
        hs = HistoryStore(reg, interval_s=1.0)
        for i in range(60):
            g.set(0.92 if i >= 45 else 0.50)
            hs.scrape(now=T0 + i)
        firings = AnomalySentinel.replay(
            hs, signals=[self._sig()], warmup=8, min_consecutive=2)
        assert [f["signal"] for f in firings] == ["mem_used_ratio"]
        # ...while a flat series — even NEAR FULL — is a steady
        # state, not an anomaly (the alarm pages on motion, not level)
        reg2 = MetricsRegistry()
        g2 = reg2.gauge("engine_mem_hbm_used_ratio")
        hs2 = HistoryStore(reg2, interval_s=1.0)
        for i in range(60):
            g2.set(0.93)
            hs2.scrape(now=T0 + i)
        assert AnomalySentinel.replay(
            hs2, signals=[self._sig()], warmup=8,
            min_consecutive=2) == []


# -- fleet rollup + placement ----------------------------------------------


def _mem_snap(tracked=10, released=4, checks=6, rejections=1,
              audit=0, unattributed=2048, headroom=1 << 20):
    return {"mem": {
        "attributed_bytes": 10_000, "unattributed_bytes": unattributed,
        "used_bytes": 10_000 + unattributed, "capacity_bytes": 1 << 22,
        "used_ratio": 0.5, "headroom_bytes": headroom,
        "high_watermark_bytes": 12_000, "growth_bytes_per_s": 0.0,
        "residual_alarm": False, "audit_problems": [],
        "segments": {"kv_pages": 8_000, "weights": 2_000},
        "stats": {"tracked_allocs": tracked,
                  "released_allocs": released,
                  "admission_checks": checks,
                  "admission_rejections": rejections,
                  "audit_failures": audit}}}


class TestFleetMem:
    def test_fold_restart_tolerance_and_rollup(self, gpt_model):
        eng = ServingEngine(gpt_model, max_slots=1, page_size=16,
                            max_seq_len=64)
        router = FleetRouter([InprocReplica("r0", eng)])
        try:
            reg = router.registry

            def c(name):
                m = reg.get(name)
                return 0 if m is None else int(m.value)

            router._fold_mem("r0", _mem_snap(tracked=10))
            assert c("fleet_mem_tracked_allocs_total") == 10
            assert c("fleet_mem_released_allocs_total") == 4
            assert c("fleet_mem_admission_checks_total") == 6
            assert c("fleet_mem_admission_rejections_total") == 1
            # monotonic growth folds the delta only
            router._fold_mem("r0", _mem_snap(tracked=14))
            assert c("fleet_mem_tracked_allocs_total") == 14
            # a BACKWARDS value = replica restart: fold the new
            # absolute, never a negative delta
            router._fold_mem("r0", _mem_snap(tracked=5))
            assert c("fleet_mem_tracked_allocs_total") == 19
            # fleet residual gauge is the MAX across replica digests
            assert int(reg.get(
                "fleet_mem_unattributed_bytes").value) == 2048
            h = router.health()["mem"]
            assert h["replicas"]["r0"]["headroom_bytes"] == 1 << 20
            assert h["segments"]["kv_pages"] == 8_000
            assert h["unattributed_bytes_max"] == 2048
            # a heartbeat with no mem section clears the inventory;
            # no digests -> rollup reads None
            router._fold_mem("r0", {})
            assert "r0" not in router._mem_digests
            assert router.health()["mem"] is None
            assert "r0" not in router._mem_seen
        finally:
            router.close()
            eng.close()

    def test_placement_headroom_term_weight_gated(self, gpt_model):
        engines = [ServingEngine(gpt_model, max_slots=1, page_size=16,
                                 max_seq_len=64) for _ in range(2)]
        reps = [InprocReplica(f"r{i}", e)
                for i, e in enumerate(engines)]
        router = FleetRouter(reps)
        try:
            # deterministic candidates: identical stubbed health
            # snapshots (live scrapes are rate-limited and racy), and
            # a no-op fold so the background scrape can't clear the
            # injected digests (these engines have no ledger)
            snap = {"state": "serving", "free_pages": 4,
                    "queued": 0, "running": 0}
            router._last_scrape = {"r0": dict(snap), "r1": dict(snap)}
            router._fold_mem = lambda name, snap: None
            # identical engines: r1 forecasts 64 MB more headroom
            router._mem_digests = {
                "r0": {"headroom_bytes": 1 << 20},
                "r1": {"headroom_bytes": 65 << 20}}
            # weight 0 (default): the term is skipped entirely,
            # placement unchanged -> deterministic name tie-break
            assert router.placement_weights["mem_headroom"] == 0.0
            assert router._pick_replica({}) == "r0"
            router.placement_weights["mem_headroom"] = 1.0
            assert router._pick_replica({}) == "r1", \
                "a nonzero weight must prefer the forecast headroom"
            # a replica with no armed ledger scores 0, not a penalty
            router._mem_digests = {"r1": {"headroom_bytes": None}}
            assert router._pick_replica({}) == "r0"
        finally:
            router.close()
            for e in engines:
                e.close()

    def test_failover_conservation_with_ledgers_armed(self, gpt_model):
        """Crash a replica mid-wave with ledgers armed everywhere:
        every request completes, compile counts stay frozen, and the
        SURVIVOR's ledger still conserves — failover re-admission
        must not strand attributed bytes."""
        engines = [_armed(gpt_model) for _ in range(2)]
        for e in engines:
            e.warmup(buckets=[24, 20], decode=True)
        frozen = [e.compile_counts() for e in engines]
        reps = [InprocReplica(f"r{i}", e)
                for i, e in enumerate(engines)]
        router = FleetRouter(reps)
        try:
            outs = router.generate(_shared_wave(),
                                   max_new_tokens=8)
            assert all(len(t) for t in outs)
            with faults.scenario(("replica_crash", {"replica": "r1"})):
                outs = router.generate(_shared_wave(seed=1),
                                       max_new_tokens=8)
            assert all(len(t) for t in outs)
            assert reps[1].state == "dead"
            assert engines[0].compile_counts() == frozen[0]
            c = engines[0].ledger.conservation(tolerance=0.01)
            assert c["ok"], f"survivor conservation broken: {c}"
            # the router folded nonzero ledger activity off heartbeats
            h = router.health()["mem"]
            assert h is not None and "r0" in h["replicas"]
        finally:
            router.close()
            for e in engines:
                e.close()


# -- fleet_top columns -----------------------------------------------------


class TestFleetTopMemColumns:
    def test_render_mem_and_headroom(self, tmp_path):
        ft = importlib.import_module("fleet_top")
        reg = MetricsRegistry()
        reg.counter("fleet_tokens_out_total").inc(10)
        hs = HistoryStore(reg, interval_s=1.0)
        for i in range(5):
            hs.scrape(now=T0 + i)
        hs.save(str(tmp_path / "history_snapshot.json"))
        base = {"state": "serving", "incarnation": 1, "queued": 0,
                "running": 0, "free_pages": 9, "scrape_age_s": 0.01,
                "lost": False, "quarantined": False}
        with open(tmp_path / "health.json", "w") as f:
            json.dump({
                "queue_depth": 0, "pending": 0, "lost": [],
                "replicas": {"r0": dict(base), "r1": dict(base)},
                "mem": {
                    "replicas": {"r0": {"used_ratio": 0.425,
                                        "headroom_bytes": 512 << 20,
                                        "residual_alarm": True}},
                    "segments": {"kv_pages": 1024},
                    "unattributed_bytes_max": 0}}, f)
        frame = ft.collect_snapshot(str(tmp_path))
        text = ft.render(frame)
        assert "MEM%" in text and "HEADROOM" in text
        r0 = [ln for ln in text.splitlines()
              if ln.strip().startswith("r0")][0]
        assert "42.5" in r0 and "512.0M" in r0
        assert "M" in r0.split()[-1], \
            "residual alarm must raise the M flag"
        # r1 has no ledger armed: renders "-", never crashes
        r1 = [ln for ln in text.splitlines()
              if ln.strip().startswith("r1")][0]
        assert " - " in r1


# -- tools/mem_diff.py -----------------------------------------------------


def _write_snap(path, segments, unattributed=0):
    att = sum(segments.values())
    doc = {"memledger": 1, "name": "t",
           "digest": {"segments": segments, "attributed_bytes": att,
                      "unattributed_bytes": unattributed},
           "tree": {}}
    with open(path, "w") as f:
        json.dump(doc, f)
    return str(path)


class TestMemDiff:
    @pytest.fixture(scope="class")
    def md(self):
        return importlib.import_module("mem_diff")

    def test_gate_both_directions(self, md, tmp_path, capsys):
        a = _write_snap(tmp_path / "a.json",
                        {"kv_pages": 1000, "weights": 500},
                        unattributed=100)
        b = _write_snap(tmp_path / "b.json",
                        {"kv_pages": 1000, "weights": 100},
                        unattributed=400)
        assert md.main([a, a, "--quiet", "--fail-on",
                        "segment:unattributed>+50%"]) == 0
        rep = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rep["ok"] and not rep["vacuous"]
        # +300% residual growth trips >
        assert md.main([a, b, "--quiet", "--fail-on",
                        "segment:unattributed>+50%"]) == 1
        rep = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rep["failures"][0]["delta_pct"] == pytest.approx(300.0)
        # the weights collapse reads through a < gate
        assert md.main([a, b, "--quiet", "--fail-on",
                        "segment:weights<-50%"]) == 1
        capsys.readouterr()

    def test_new_segment_reads_as_max_drift(self, md, tmp_path,
                                            capsys):
        a = _write_snap(tmp_path / "a2.json", {"kv_pages": 100})
        b = _write_snap(tmp_path / "b2.json",
                        {"kv_pages": 100, "spec_draft_pool": 50})
        assert md.main([a, b, "--quiet", "--fail-on",
                        "segment:spec_draft_pool>+50%"]) == 1
        capsys.readouterr()

    def test_vacuous_comparison_fails(self, md, tmp_path, capsys):
        e = _write_snap(tmp_path / "e.json", {})
        assert md.main([e, e, "--quiet"]) == 1
        rep = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])
        assert rep["vacuous"] and not rep["ok"]

    def test_bad_spec_rejected(self, md):
        with pytest.raises(Exception):
            md.parse_spec("unattributed>+50%")   # missing segment:
