"""Round-6 regression tests for the round-5 advisor findings:

1. chunked_ce / mlm_gather_capacity aux dicts carry the Parameter
   itself on the EAGER path (a fresh Tensor(w._value) is a detached
   tape leaf: loss.backward() silently dropped the tied-embedding /
   head grads), while the traced path keeps snapshotting values.
2. LlamaModel/LlamaAttention raise a ValueError up front when
   cache_index is given without cache (was a TypeError deep in
   apply_op).
3. DataLoader(use_process_workers=True, num_workers=0) raises in
   __init__ instead of silently ignoring the opt-in.
(4. the _gathered_mlm_loss overflow counter is asserted in
   test_mlm_gather.py, next to the capacity tests.)
"""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.tensor import Tensor


# ---------- 1. eager backward reaches the smuggled head weights ----------

def test_gpt_chunked_ce_eager_backward_reaches_tied_embedding():
    from paddle_tpu.nlp.gpt import (GPTForCausalLM,
                                    GPTPretrainingCriterion,
                                    _resolve_config)
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config(
        "gpt-tiny", chunked_ce=16, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0))
    m.train()
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, m.config.vocab_size, (2, 16)), jnp.int32)
    loss = GPTPretrainingCriterion()(m(Tensor(ids)), Tensor(ids))
    loss.backward()
    g = m.gpt.embeddings.word_embeddings.weight.grad
    assert g is not None
    assert float(jnp.abs(g._value).max()) > 0


@pytest.mark.parametrize("tie", [True, False])
def test_llama_chunked_ce_eager_backward_reaches_head(tie):
    from paddle_tpu.nlp.gpt import GPTPretrainingCriterion
    from paddle_tpu.nlp.llama import LlamaForCausalLM, _resolve_config
    paddle.seed(0)
    m = LlamaForCausalLM(_resolve_config(
        "llama-tiny", chunked_ce=16, tie_word_embeddings=tie,
        vocab_size=256, max_position_embeddings=64))
    m.train()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 256, (2, 16)),
                      jnp.int32)
    loss = GPTPretrainingCriterion()(m(Tensor(ids)), Tensor(ids))
    loss.backward()
    w, tied = m._head_weight()
    assert tied is tie
    assert w.grad is not None
    assert float(jnp.abs(w.grad._value).max()) > 0


def test_bert_mlm_gather_eager_backward_reaches_head():
    from paddle_tpu.nlp.bert import (BertConfig, BertForPretraining,
                                     BertPretrainingCriterion)
    paddle.seed(0)
    m = BertForPretraining(BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, mlm_gather_capacity=0.3))
    m.train()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 16)),
                      jnp.int32)
    lbl = np.full((2, 16), -100, np.int32)
    lbl[:, :4] = 7
    loss = BertPretrainingCriterion()(
        m(Tensor(ids)), Tensor(jnp.asarray(lbl)),
        Tensor(jnp.asarray([0, 1], jnp.int32)))
    loss.backward()
    for name, p in (
            ("transform.weight", m.cls.predictions.transform.weight),
            ("layer_norm.weight", m.cls.predictions.layer_norm.weight),
            ("tied embedding",
             m.bert.embeddings.word_embeddings.weight)):
        assert p.grad is not None, name
        assert float(jnp.abs(p.grad._value).max()) > 0, name


def test_traced_path_still_trains():
    """The Engine/jit path must keep its exact-parity contract after
    the eager fix (the tracer branch still snapshots values)."""
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.nlp.gpt import (GPTForCausalLM,
                                    GPTPretrainingCriterion,
                                    _resolve_config)
    from paddle_tpu.optimizer import AdamW
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config(
        "gpt-tiny", chunked_ce=16, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0))
    m.train()
    eng = Engine(m, loss=GPTPretrainingCriterion(),
                 optimizer=AdamW(1e-3, parameters=m.parameters()))
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, m.config.vocab_size, (2, 16)), jnp.int32)
    w0 = np.asarray(m.gpt.embeddings.word_embeddings.weight._value).copy()
    l0, _ = eng.train_batch([ids], [ids])
    l1, _ = eng.train_batch([ids], [ids])
    assert np.isfinite(float(l0)) and np.isfinite(float(l1))
    eng.sync_to_layer()
    w1 = np.asarray(m.gpt.embeddings.word_embeddings.weight._value)
    assert np.abs(w1 - w0).max() > 0  # the tied weight DID update


# ---------- 2. llama cache_index-without-cache guard ----------

def test_llama_cache_index_without_cache_raises():
    from paddle_tpu.nlp.llama import LlamaForCausalLM, _resolve_config
    paddle.seed(0)
    m = LlamaForCausalLM(_resolve_config(
        "llama-tiny", vocab_size=256, max_position_embeddings=64))
    m.eval()
    ids = jnp.asarray([[1, 2, 3]], jnp.int32)
    with pytest.raises(ValueError, match="cache_index"):
        m(Tensor(ids), cache_index=0)
    with pytest.raises(ValueError, match="cache_index"):
        m.llama(Tensor(ids), cache_index=0)


# ---------- 3. DataLoader process-worker opt-in validation ----------

def test_dataloader_process_workers_without_workers_raises():
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.dataset import Dataset

    class DS(Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            return np.zeros(3, np.float32)

    with pytest.raises(ValueError, match="num_workers"):
        DataLoader(DS(), use_process_workers=True, num_workers=0)
    # the valid opt-in shape still constructs
    DataLoader(DS(), use_process_workers=True, num_workers=1)
