"""Vision model zoo forward shapes + trainability (SURVEY §2.9)."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.tensor import Tensor
from paddle_tpu.vision import models as M


def _img(b=2, c=3, hw=64, seed=0):
    rng = np.random.default_rng(seed)
    return Tensor(jnp.asarray(rng.standard_normal((b, c, hw, hw)),
                              dtype=jnp.float32))


# constructor, input size, kwargs — small classes to keep CPU time low
# (ctor, hw, kwargs, slow) — slow marks the heavier sibling of a family
# whose cheaper member stays in the default run
_CASES = [
    (M.vgg11, 64, {}, False),
    (M.vgg16, 64, {"batch_norm": True}, True),
    (M.alexnet, 96, {}, False),
    (M.squeezenet1_0, 64, {}, False),
    (M.squeezenet1_1, 64, {}, True),
    (M.mobilenet_v1, 64, {"scale": 0.25}, False),
    (M.mobilenet_v2, 64, {"scale": 0.25}, False),
    (M.mobilenet_v3_small, 64, {"scale": 0.5}, False),
    (M.mobilenet_v3_large, 64, {"scale": 0.5}, True),
    (M.densenet121, 64, {}, True),
    (M.shufflenet_v2_x0_25, 64, {}, False),
    (M.shufflenet_v2_swish, 64, {}, True),
    (M.inception_v3, 128, {}, True),
]


@pytest.mark.parametrize(
    "ctor,hw,kw",
    [pytest.param(c, h, k,
                  marks=[pytest.mark.slow] if sl else [])
     for c, h, k, sl in _CASES],
    ids=[c[0].__name__ for c in _CASES])
def test_forward_shape(ctor, hw, kw):
    # jitted functional forward: the production (Engine/jit) path, and one
    # persistent-cached compile instead of thousands of eager dispatches
    from tests.conftest import jit_forward
    paddle.seed(0)
    m = ctor(num_classes=10, **kw)
    m.eval()
    out = jit_forward(m, _img(hw=hw)._value)
    assert tuple(out.shape) == (2, 10)
    assert bool(jnp.isfinite(out).all())


def test_googlenet_aux_heads():
    from tests.conftest import jit_forward
    paddle.seed(0)
    m = M.googlenet(num_classes=10)
    m.train()
    out, aux1, aux2 = jit_forward(m, _img(hw=96)._value)
    assert tuple(out.shape) == tuple(aux1.shape) == tuple(aux2.shape) \
        == (2, 10)
    m.eval()
    out = jit_forward(m, _img(hw=96)._value)
    assert tuple(out.shape) == (2, 10)


def test_mobilenet_trains():
    """one of the new families must actually learn (grad path sound)."""
    from paddle_tpu.hapi.engine import Engine
    paddle.seed(0)
    m = M.mobilenet_v2(scale=0.25, num_classes=2)
    opt = paddle.optimizer.Adam(2e-3, parameters=m.parameters())
    eng = Engine(m, loss=paddle.nn.CrossEntropyLoss(), optimizer=opt)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 3, 32, 32)).astype(np.float32)
    x[:4] += 2.0
    y = np.array([1] * 4 + [0] * 4)
    losses = [float(eng.train_batch([jnp.asarray(x)], [jnp.asarray(y)])[0])
              for _ in range(25)]
    assert losses[-1] < losses[0] * 0.5, losses[::6]


def test_state_dict_roundtrip():
    paddle.seed(0)
    m = M.shufflenet_v2_x0_25(num_classes=4)
    m.eval()
    x = _img(hw=32)
    want = np.asarray(m(x)._value)
    sd = {k: np.asarray(v._value) for k, v in m.state_dict().items()}
    paddle.seed(123)
    m2 = M.shufflenet_v2_x0_25(num_classes=4)
    m2.eval()
    m2.set_state_dict(sd)
    np.testing.assert_allclose(np.asarray(m2(x)._value), want, atol=1e-6)


def test_pretrained_raises():
    with pytest.raises(NotImplementedError):
        M.vgg16(pretrained=True)
    with pytest.raises(NotImplementedError):
        M.mobilenet_v2(pretrained=True)


def test_squeezenet_bad_version_raises():
    with pytest.raises(ValueError):
        M.SqueezeNet(version="1_0")


def test_s2d_stem_exactly_equals_7x7():
    """SpaceToDepthStem with converted weights reproduces the 7x7/s2 conv
    bit-for-bit (MLPerf conv0 space-to-depth equivalence)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.vision.models.resnet import (SpaceToDepthStem,
                                                 s2d_weights_from_7x7)
    from paddle_tpu import nn
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((2, 3, 32, 32)).astype('float32'))
    conv7 = nn.Conv2D(3, 16, 7, stride=2, padding=3, bias_attr=False)
    stem = SpaceToDepthStem(16)
    stem.conv.weight.set_value(
        s2d_weights_from_7x7(conv7.weight.numpy()))
    ref = conv7(x).numpy()
    got = stem(x).numpy()
    assert ref.shape == got.shape == (2, 16, 16, 16)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_resnet_s2d_stem_trains():
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.vision.models import resnet18
    paddle.seed(0)
    net = resnet18(num_classes=10, s2d_stem=True)
    net.train()
    eng = Engine(net, loss=paddle.nn.CrossEntropyLoss(),
                 optimizer=paddle.optimizer.Momentum(
                     0.05, parameters=net.parameters()))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 3, 64, 64)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (2,)))
    l0, _ = eng.train_batch([x], [y])
    l1, _ = eng.train_batch([x], [y])
    assert np.isfinite(float(l0)) and np.isfinite(float(l1))


def test_s2d_stem_rejects_odd_sizes():
    import numpy as np
    import pytest
    import paddle_tpu as paddle
    from paddle_tpu.vision.models.resnet import SpaceToDepthStem
    stem = SpaceToDepthStem(8)
    x = paddle.to_tensor(np.zeros((1, 3, 33, 32), np.float32))
    with pytest.raises(ValueError, match="even input"):
        stem(x)
