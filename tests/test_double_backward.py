"""paddle.grad(create_graph=True) — double backward through the eager
tape via functional replay (ref: paddle.grad double-grad, the
gradient-penalty workhorse).

The replay re-derives gradients as a function of the inputs, so the
residual term of the second derivative is exact; recording the stored
pullback instead would give d2(x^2)/dx2 == 0.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle


def _t(v, sg=False):
    return paddle.to_tensor(np.asarray(v, np.float32), stop_gradient=sg)


def test_second_derivative_exact():
    x = _t([2.0, -1.0, 0.5])
    y = x * x * x
    (g,) = paddle.grad(y, x, create_graph=True)
    np.testing.assert_allclose(g.numpy(), 3 * np.array([4.0, 1.0, 0.25]),
                               rtol=1e-6)
    g.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               6 * np.array([2.0, -1.0, 0.5]), rtol=1e-6)


def test_residual_term_not_dropped():
    """The canonical failure of naive vjp-of-vjp: y = x*x has
    d2y/dx2 = 2, which lives entirely in the residual term."""
    x = _t([3.0])
    (g,) = paddle.grad(x * x, x, create_graph=True)
    g.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0], rtol=1e-6)


def test_triple_nesting():
    """grad of grad of grad: d3(x^4)/dx3 = 24x."""
    x = _t([1.5])
    (g1,) = paddle.grad(x * x * x * x, x, create_graph=True)
    (g2,) = paddle.grad(g1, x, create_graph=True)
    g2.backward()
    np.testing.assert_allclose(x.grad.numpy(), [24 * 1.5], rtol=1e-5)


def test_wgan_gp_param_grads_match_functional():
    """Gradient-penalty loss: second-order grads into the layer params
    must equal the pure jax.grad reference."""
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.Tanh(),
                               paddle.nn.Linear(8, 1))
    rng = np.random.default_rng(0)
    xin_np = rng.standard_normal((3, 4)).astype(np.float32)

    xin = _t(xin_np)
    out = net(xin)
    (gx,) = paddle.grad(out.sum(), xin, create_graph=True)
    gp = ((gx * gx).sum(axis=1).sqrt() - 1.0)
    ((gp * gp).mean()).backward()

    # functional reference over the same params
    from paddle_tpu.nn.layer import functional_call
    from paddle_tpu.tensor import Tensor
    params, buffers = net.raw_state()

    def penalty(p, x):
        def f(xx):
            o = functional_call(net, p, buffers, Tensor(xx))
            return jnp.sum(o._value)
        g = jax.grad(f)(x)
        gp = jnp.sqrt(jnp.sum(g * g, axis=1)) - 1.0
        return jnp.mean(gp * gp)

    ref = jax.grad(penalty)(params, jnp.asarray(xin_np))
    for name, p in net.named_parameters():
        np.testing.assert_allclose(np.asarray(p.grad.numpy()),
                                   np.asarray(ref[name]), rtol=1e-4,
                                   atol=1e-5, err_msg=name)


def test_unused_input_allow_unused():
    x = _t([1.0])
    z = _t([5.0])
    y = x * 2.0
    with pytest.raises(ValueError, match="allow_unused"):
        paddle.grad(y, [x, z], create_graph=True)
    gx, gz = paddle.grad(y, [x, z], create_graph=True, allow_unused=True)
    assert gz is None
    np.testing.assert_allclose(gx.numpy(), [2.0])


def test_grad_outputs_seed():
    x = _t([1.0, 2.0])
    y = x * x
    (g,) = paddle.grad(y, x, grad_outputs=_t([3.0, 5.0], sg=True),
                       create_graph=True)
    np.testing.assert_allclose(g.numpy(), [6.0, 20.0], rtol=1e-6)
    g.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [6.0, 10.0], rtol=1e-6)


def test_first_order_path_unchanged():
    x = _t([4.0])
    (g,) = paddle.grad(x * x, x)            # create_graph=False default
    np.testing.assert_allclose(g.numpy(), [8.0])
    assert x.grad is None                   # grad() doesn't write .grad


def test_non_leaf_input():
    """grad w.r.t. an INTERMEDIATE tensor: the replay must not clobber
    the seeded value with the recomputed producer output."""
    x = _t([3.0])
    h = x * x
    y = (h * h).sum()
    (gh,) = paddle.grad(y, h, create_graph=True)
    np.testing.assert_allclose(gh.numpy(), [2 * 9.0], rtol=1e-6)  # 2h
    gh.backward()
    # d(2h)/dh == 2, deposited on... h is non-leaf; grads flow to x:
    # d(2h)/dx = 2 * dh/dx = 4x
    np.testing.assert_allclose(x.grad.numpy(), [12.0], rtol=1e-6)


def test_duplicate_inputs_consistent():
    x = _t([2.0])
    y = (x * x).sum()
    g1, g2 = paddle.grad(y, [x, x], create_graph=True)
    np.testing.assert_allclose(g1.numpy(), [4.0], rtol=1e-6)
    np.testing.assert_allclose(g2.numpy(), [4.0], rtol=1e-6)


def test_create_graph_inside_no_grad():
    """create_graph means BUILD the graph even under no_grad (the
    reference semantics) — the later backward must not be a no-op."""
    x = _t([2.0])
    y = x * x * x
    with paddle.no_grad():
        (g,) = paddle.grad(y, x, create_graph=True)
    g.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [12.0], rtol=1e-6)


def test_grid_sample_unknown_mode_rejected():
    from paddle_tpu.nn import functional as F
    with pytest.raises(ValueError, match="mode"):
        F.grid_sample(_t(np.zeros((1, 1, 2, 2)), sg=True),
                      _t(np.zeros((1, 1, 1, 2)), sg=True), mode="bicubic")
