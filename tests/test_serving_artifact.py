"""AOT serving artifacts (jit/serving_artifact.py) — ISSUE 21.

Pins the round-21 contracts:

- an artifact-booted engine serves TOKEN-EXACT vs a traced-boot
  engine over the same model instance (GPT + Llama/GQA, greedy +
  seeded top-k, spec-armed), with ZERO post-load Python traces and
  zero unexpected retraces;
- the store is crash-safe end to end: blobs staged + checksummed,
  directory renamed, COMPLETE marker strictly last — a simulated
  crash mid-export leaves only unmarked debris the loader refuses;
- the fallback ladder is LOUD and total: every torn / stale / corrupt
  / wrong-device / expired case raises the exact ArtifactError reason
  from ``load_artifact``, and ``warm_boot`` counts it in
  ``serve_aot_fallback_total{reason}`` before serving traced — never
  a wrong program, never a silent slow boot;
- dormancy: no store configured (or the kill switch off) keeps the
  engine's metric surface byte-identical to pre-artifact builds;
- chaos: kill-mid-export, byte-flip, and stale-fingerprint fleets
  come up serving token-exact with zero lost requests, and the boot
  mode rides heartbeats into router health + fleet_top's BOOT column.
"""
import json
import os
import shutil
import sys
import time

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.io.atomic import has_marker
from paddle_tpu.jit.serving_artifact import (
    ArtifactError, artifact_fingerprint, export_artifact,
    load_artifact, warm_boot)
from paddle_tpu.nlp.generation import generate
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
from paddle_tpu.nlp.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nlp.serving import ServingEngine

NEW_TOK = 8
LENS = (5, 8)


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    return m


@pytest.fixture(scope="module")
def llama_model():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig(
        vocab_size=256, hidden_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=128, max_position_embeddings=128))
    m.eval()
    return m


def _prompts(lens, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, (n,)).astype(np.int32) for n in lens]


def _greedy_ref(model, prompts, new_tok):
    out = []
    for p in prompts:
        ids = generate(model, jnp.asarray(p)[None, :],
                       max_new_tokens=new_tok, temperature=0.0)
        out.append(np.asarray(ids._value)[0, len(p):].tolist())
    return out


def _engine(model, **kw):
    d = dict(max_slots=2, page_size=16, max_seq_len=64,
             steps_per_dispatch=4)
    d.update(kw)
    return ServingEngine(model, **d)


def _counter(reg, name, labels=None):
    c = reg.get(name, labels)
    return 0 if c is None else int(c.value)


def _aot_series(reg):
    return sorted(s.name for s in reg.series()
                  if s.name.startswith("serve_aot"))


def _art_dir(root):
    arts = [os.path.join(root, n) for n in sorted(os.listdir(root))
            if n.startswith("art-")]
    assert arts, f"no artifact under {root}"
    return arts[-1]


def _copy_store(root, dst):
    dst = str(dst)
    shutil.copytree(root, dst)
    return dst


# -- one traced-boot GPT engine + its exported store, shared ----------------

@pytest.fixture(scope="module")
def gpt_store(gpt_model, tmp_path_factory):
    root = str(tmp_path_factory.mktemp("aot_store"))
    eng = _engine(gpt_model)
    eng.warmup(buckets=LENS, decode=True)
    art = export_artifact(eng, root)
    prompts = _prompts(LENS)
    refs = eng.generate(prompts, max_new_tokens=NEW_TOK)
    yield {"root": root, "artifact": art, "engine": eng,
           "prompts": prompts, "refs": refs}
    eng.close()


# -- corruption recipes (applied to a private COPY of the store) ------------

def _corrupt_unmarked(root):
    os.remove(os.path.join(_art_dir(root), "COMPLETE"))


def _corrupt_blob_missing(root):
    os.remove(os.path.join(_art_dir(root), "decode.stablehlo"))


def _corrupt_manifest(root):
    with open(os.path.join(_art_dir(root), "manifest.json"), "w") as f:
        f.write("{ not json")


def _corrupt_byte_flip(root):
    path = os.path.join(_art_dir(root), "decode.stablehlo")
    with open(path, "rb") as f:
        raw = bytearray(f.read())
    raw[len(raw) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(raw)


def _edit_manifest(root, fn):
    mpath = os.path.join(_art_dir(root), "manifest.json")
    with open(mpath) as f:
        doc = json.load(f)
    fn(doc)
    with open(mpath, "w") as f:
        json.dump(doc, f)


def _corrupt_wrong_device(root):
    _edit_manifest(root, lambda d: d["fingerprint"].update(
        device={"platform": "tpu", "kind": "TPU v4"}))


def _corrupt_stale_config(root):
    # the post-config-change case: the store was exported for a
    # different model architecture
    _edit_manifest(root, lambda d: d["fingerprint"]["config"].update(
        hidden_size=4096))


def _corrupt_version(root):
    _edit_manifest(root, lambda d: d.update(version=999))


CORRUPTIONS = [
    (_corrupt_unmarked, "torn"),
    (_corrupt_blob_missing, "torn"),
    (_corrupt_manifest, "bad_manifest"),
    (_corrupt_byte_flip, "bad_checksum"),
    (_corrupt_wrong_device, "wrong_device"),
    (_corrupt_stale_config, "stale_fingerprint"),
    (_corrupt_version, "stale_fingerprint"),
]


# -- export / store layout --------------------------------------------------

class TestExport:
    def test_store_layout_published_and_checksummed(self, gpt_store):
        art = gpt_store["artifact"]
        assert has_marker(art)
        with open(os.path.join(art, "manifest.json")) as f:
            manifest = json.load(f)
        blobs = manifest["blobs"]
        # the full warmed program set: one prefill bucket (5 and 8
        # both normalize to bucket 8) + decode
        assert "decode" in blobs
        assert any(s.startswith("prefill_") for s in blobs)
        import hashlib
        for site, meta in blobs.items():
            with open(os.path.join(art, meta["file"]), "rb") as f:
                raw = f.read()
            assert hashlib.sha256(raw).hexdigest() == meta["sha256"]
            assert len(raw) == meta["bytes"]
        # no staging debris after a clean publish
        assert not [n for n in os.listdir(gpt_store["root"])
                    if n.startswith(".stage-")]

    def test_export_is_idempotent(self, gpt_store):
        # same fingerprint + same sites -> the existing artifact, no
        # second dir (a fleet sharing a store exports once)
        again = export_artifact(gpt_store["engine"], gpt_store["root"])
        assert again == gpt_store["artifact"]
        assert len([n for n in os.listdir(gpt_store["root"])
                    if n.startswith("art-")]) == 1

    def test_export_requires_warmed_engine(self, gpt_model, tmp_path):
        eng = _engine(gpt_model)
        with pytest.raises(RuntimeError, match="warmed"):
            export_artifact(eng, str(tmp_path))
        eng.close()

    def test_fingerprint_covers_the_load_bearing_fields(self,
                                                        gpt_store):
        fp = artifact_fingerprint(gpt_store["engine"])
        for key in ("config", "cache_dtype", "page_size",
                    "max_seq_len", "steps_per_dispatch", "sampling",
                    "spec", "prefix", "jax", "jaxlib", "device"):
            assert key in fp, key


# -- the token-exactness matrix ---------------------------------------------

class TestArtifactBootTokenExact:
    def test_gpt_greedy_token_exact_zero_traces(self, gpt_model,
                                                gpt_store):
        eng = _engine(gpt_model)
        info = warm_boot(eng, buckets=LENS,
                         artifact_dir=gpt_store["root"])
        assert info["mode"] == "aot"
        assert info["artifact"] == os.path.basename(
            gpt_store["artifact"])
        assert _counter(eng.registry, "serve_aot_loads_total") == 1
        assert _aot_series(eng.registry) == ["serve_aot_loads_total"]
        assert eng.warmed
        frozen = eng.compile_counts()
        outs = eng.generate(gpt_store["prompts"],
                            max_new_tokens=NEW_TOK)
        # exact vs the traced-boot engine AND the dense reference
        assert outs == gpt_store["refs"]
        assert outs == _greedy_ref(gpt_model, gpt_store["prompts"],
                                   NEW_TOK)
        assert eng.compile_counts() == frozen
        assert eng.tracer.unexpected_retraces() == 0
        eng.close()

    def test_gpt_topk_token_exact(self, gpt_model, tmp_path):
        kw = dict(temperature=0.9, top_k=5, seed=7)
        a = _engine(gpt_model, **kw)
        a.warmup(buckets=LENS, decode=True)
        root = str(tmp_path / "store")
        export_artifact(a, root)
        prompts = _prompts(LENS, seed=3)
        refs = a.generate(prompts, max_new_tokens=NEW_TOK)
        b = _engine(gpt_model, **kw)
        assert warm_boot(b, buckets=LENS,
                         artifact_dir=root)["mode"] == "aot"
        frozen = b.compile_counts()
        assert b.generate(prompts, max_new_tokens=NEW_TOK) == refs
        assert b.compile_counts() == frozen
        a.close()
        b.close()

    def test_llama_gqa_greedy_token_exact(self, llama_model, tmp_path):
        a = _engine(llama_model)
        a.warmup(buckets=LENS, decode=True)
        root = str(tmp_path / "store")
        export_artifact(a, root)
        prompts = _prompts(LENS, seed=1)
        refs = a.generate(prompts, max_new_tokens=NEW_TOK)
        b = _engine(llama_model)
        assert warm_boot(b, buckets=LENS,
                         artifact_dir=root)["mode"] == "aot"
        frozen = b.compile_counts()
        assert b.generate(prompts, max_new_tokens=NEW_TOK) == refs
        assert b.compile_counts() == frozen
        assert b.tracer.unexpected_retraces() == 0
        a.close()
        b.close()

    def test_spec_armed_artifact_round_trip(self, gpt_model, tmp_path):
        kw = dict(spec_decode=True, spec_k=4, spec_draft="ngram")
        a = _engine(gpt_model, **kw)
        a.warmup(buckets=LENS, decode=True)
        root = str(tmp_path / "store")
        art = export_artifact(a, root)
        with open(os.path.join(art, "manifest.json")) as f:
            manifest = json.load(f)
        assert "spec_verify" in manifest["blobs"]
        assert manifest["warmed"]["spec"]
        prompts = _prompts(LENS, seed=2)
        refs = a.generate(prompts, max_new_tokens=NEW_TOK)
        b = _engine(gpt_model, **kw)
        assert warm_boot(b, buckets=LENS,
                         artifact_dir=root)["mode"] == "aot"
        assert b._warmed_spec
        assert b.generate(prompts, max_new_tokens=NEW_TOK) == refs
        a.close()
        b.close()

    def test_bucket_top_up_is_traced_and_loud(self, gpt_model,
                                              gpt_store):
        # ask for a bucket the artifact does not carry: the loader
        # installs what it has and warms the rest through the traced
        # path — visible in compile_counts, never a wrong program
        eng = _engine(gpt_model, max_seq_len=64)
        info = warm_boot(eng, buckets=[*LENS, 17],
                         artifact_dir=gpt_store["root"])
        assert info["mode"] == "aot"
        assert eng._bucket_for(17) in eng._warmed_buckets
        prompts = _prompts((5, 17), seed=4)
        refs = _greedy_ref(gpt_model, prompts, NEW_TOK)
        assert eng.generate(prompts, max_new_tokens=NEW_TOK) == refs
        eng.close()


# -- load_artifact fallback matrix (no warmups — pure refusal paths) --------

class TestLoadFallbackMatrix:
    def test_missing_store(self, gpt_model, tmp_path):
        eng = _engine(gpt_model)
        with pytest.raises(ArtifactError) as ei:
            load_artifact(eng, str(tmp_path / "nope"))
        assert ei.value.reason == "missing"
        eng.close()

    def test_empty_store(self, gpt_model, tmp_path):
        eng = _engine(gpt_model)
        with pytest.raises(ArtifactError) as ei:
            load_artifact(eng, str(tmp_path))
        assert ei.value.reason == "missing"
        eng.close()

    @pytest.mark.parametrize(
        "corrupt,reason", CORRUPTIONS,
        ids=[f"{c.__name__[9:]}->{r}" for c, r in CORRUPTIONS])
    def test_corruption_reasons(self, gpt_model, gpt_store, tmp_path,
                                corrupt, reason):
        root = _copy_store(gpt_store["root"], tmp_path / "store")
        corrupt(root)
        eng = _engine(gpt_model)
        with pytest.raises(ArtifactError) as ei:
            load_artifact(eng, root)
        assert ei.value.reason == reason, str(ei.value)
        # refusal before install: the engine is untouched
        assert not eng.warmed
        eng.close()

    @pytest.mark.parametrize("kw,field", [
        ({"steps_per_dispatch": 2}, "steps_per_dispatch"),
        ({"page_size": 8}, "page_size"),
        ({"max_seq_len": 48}, "max_seq_len"),
        ({"cache_dtype": "bfloat16"}, "cache_dtype"),
        ({"temperature": 0.9, "top_k": 5}, "sampling"),
        ({"spec_decode": True, "spec_k": 4, "spec_draft": "ngram"},
         "spec"),
    ])
    def test_stale_fingerprint_per_field(self, gpt_model, gpt_store,
                                         kw, field):
        # the live engine changed since export: every load-bearing
        # field lands on stale_fingerprint and NAMES the field
        eng = _engine(gpt_model, **kw)
        with pytest.raises(ArtifactError) as ei:
            load_artifact(eng, gpt_store["root"])
        assert ei.value.reason == "stale_fingerprint"
        assert field in str(ei.value)
        eng.close()

    def test_wrong_model_is_stale(self, llama_model, gpt_store):
        eng = _engine(llama_model)
        with pytest.raises(ArtifactError) as ei:
            load_artifact(eng, gpt_store["root"])
        assert ei.value.reason == "stale_fingerprint"
        eng.close()

    def test_expired_ttl(self, gpt_model, gpt_store):
        eng = _engine(gpt_model)
        with pytest.raises(ArtifactError) as ei:
            load_artifact(eng, gpt_store["root"], ttl_s=0.0)
        assert ei.value.reason == "expired"
        eng.close()

    def test_install_error_rolls_back_to_cold(self, gpt_model,
                                              gpt_store, monkeypatch):
        eng = _engine(gpt_model)

        def boom(name, call):
            raise RuntimeError("install boom")

        monkeypatch.setattr(eng, "_install_aot_program", boom)
        with pytest.raises(ArtifactError) as ei:
            load_artifact(eng, gpt_store["root"])
        assert ei.value.reason == "install_error"
        # the program table is back to build-on-first-use: nothing
        # half-installed can serve
        assert not eng.warmed
        assert not eng._warmed_buckets
        eng.close()


# -- warm_boot: the loud fallback + dormancy contracts ----------------------

class TestWarmBootLadder:
    @pytest.mark.parametrize(
        "corrupt,reason", CORRUPTIONS,
        ids=[f"{c.__name__[9:]}->{r}" for c, r in CORRUPTIONS])
    def test_every_reason_is_counted(self, gpt_model, gpt_store,
                                     tmp_path, monkeypatch, corrupt,
                                     reason):
        root = _copy_store(gpt_store["root"], tmp_path / "store")
        corrupt(root)
        eng = _engine(gpt_model)
        calls = []
        monkeypatch.setattr(eng, "warmup",
                            lambda **kw: calls.append(kw))
        info = warm_boot(eng, buckets=LENS, artifact_dir=root,
                         export=False)
        assert info["mode"] == "traced" and calls
        assert _counter(eng.registry, "serve_aot_fallback_total",
                        {"reason": reason}) == 1
        assert eng.boot_info["mode"] == "traced"
        eng.close()

    def test_no_store_is_dormant(self, gpt_model, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_AOT_DIR", raising=False)
        eng = _engine(gpt_model)
        calls = []
        monkeypatch.setattr(eng, "warmup",
                            lambda **kw: calls.append(kw))
        info = warm_boot(eng, buckets=LENS)
        assert info["mode"] == "traced" and calls
        # byte-identical metric surface: no serve_aot_* series at all
        assert _aot_series(eng.registry) == []
        eng.close()

    def test_kill_switch_disables_everything(self, gpt_model,
                                             gpt_store, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AOT_ARTIFACTS", "0")
        eng = _engine(gpt_model)
        calls = []
        monkeypatch.setattr(eng, "warmup",
                            lambda **kw: calls.append(kw))
        info = warm_boot(eng, buckets=LENS,
                         artifact_dir=gpt_store["root"])
        assert info["mode"] == "traced" and calls
        assert _aot_series(eng.registry) == []
        eng.close()

    def test_env_dir_resolves_store(self, gpt_model, gpt_store,
                                    monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AOT_DIR", gpt_store["root"])
        eng = _engine(gpt_model)
        info = warm_boot(eng, buckets=LENS)
        assert info["mode"] == "aot"
        eng.close()

    def test_export_failure_is_counted_not_fatal(self, gpt_model,
                                                 tmp_path,
                                                 monkeypatch):
        # empty store -> missing fallback; the post-boot export then
        # fails (warmup was stubbed, engine never warmed) — counted,
        # boot survives
        eng = _engine(gpt_model)
        monkeypatch.setattr(eng, "warmup", lambda **kw: None)
        info = warm_boot(eng, buckets=LENS,
                         artifact_dir=str(tmp_path / "store"))
        assert info["mode"] == "traced"
        assert _counter(eng.registry, "serve_aot_fallback_total",
                        {"reason": "missing"}) == 1
        assert _counter(eng.registry,
                        "serve_aot_export_failures_total") == 1
        eng.close()

    def test_boot_info_in_health(self, gpt_store):
        h = gpt_store["engine"].health()
        assert h["boot"] == gpt_store["engine"].boot_info


# -- chaos: torn/stale/corrupt fleets still serve, zero lost ----------------

@pytest.mark.chaos
class TestArtifactChaos:
    def test_crash_mid_export_boots_traced_then_republishes(
            self, gpt_model, gpt_store, tmp_path):
        """Kill-mid-export drill: the store holds only unmarked debris
        (a staging dir + a renamed-but-unmarked artifact — the two
        crash windows). Boot refuses it loudly, serves traced,
        republishes; the NEXT boot rides the fast path."""
        root = str(tmp_path / "store")
        os.makedirs(root)
        src = gpt_store["artifact"]
        stage = os.path.join(root, ".stage-999-deadbeef-1")
        shutil.copytree(src, stage)
        os.remove(os.path.join(stage, "COMPLETE"))
        unmarked = os.path.join(root, "art-deadbeef-1")
        shutil.copytree(src, unmarked)
        os.remove(os.path.join(unmarked, "COMPLETE"))

        eng = _engine(gpt_model)
        info = warm_boot(eng, buckets=LENS, artifact_dir=root)
        assert info["mode"] == "traced"
        assert _counter(eng.registry, "serve_aot_fallback_total",
                        {"reason": "torn"}) == 1
        # traced fallback serves token-exact
        assert eng.generate(gpt_store["prompts"],
                            max_new_tokens=NEW_TOK) \
            == gpt_store["refs"]
        # ...and republished: a marked artifact now exists
        assert info["artifact"] is not None
        assert has_marker(os.path.join(root, info["artifact"]))

        b = _engine(gpt_model)
        info2 = warm_boot(b, buckets=LENS, artifact_dir=root)
        assert info2["mode"] == "aot"
        assert b.generate(gpt_store["prompts"],
                          max_new_tokens=NEW_TOK) == gpt_store["refs"]
        eng.close()
        b.close()

    def test_fleet_mixed_boot_serves_token_exact_zero_lost(
            self, gpt_model, gpt_store, tmp_path):
        """A two-replica fleet: r0 artifact-booted, r1 booted off a
        byte-flipped store (loud bad_checksum fallback). Every
        request resolves exactly once, token-exact vs the traced
        baseline — corruption costs boot time, never a token and
        never a request."""
        from paddle_tpu.serving_fleet import FleetRouter, \
            InprocReplica
        bad = _copy_store(gpt_store["root"], tmp_path / "bad")
        _corrupt_byte_flip(bad)
        e0 = _engine(gpt_model)
        assert warm_boot(e0, buckets=LENS,
                         artifact_dir=gpt_store["root"])["mode"] \
            == "aot"
        e1 = _engine(gpt_model)
        assert warm_boot(e1, buckets=LENS, artifact_dir=bad,
                         export=False)["mode"] == "traced"
        assert _counter(e1.registry, "serve_aot_fallback_total",
                        {"reason": "bad_checksum"}) == 1

        router = FleetRouter([InprocReplica("r0", e0),
                              InprocReplica("r1", e1)])
        try:
            wave = gpt_store["prompts"] * 3
            rids = [router.submit(p, NEW_TOK) for p in wave]
            by_rid = {r["id"]: r
                      for r in router.run_to_completion()}
            assert sorted(by_rid) == sorted(rids)
            refs = gpt_store["refs"] * 3
            assert all(by_rid[rid]["status"] == "ok"
                       and by_rid[rid]["tokens"] == refs[i]
                       for i, rid in enumerate(rids))
            # the boot mode rides heartbeats into router health
            deadline = time.monotonic() + 10
            reps = {}
            while time.monotonic() < deadline:
                reps = router.health()["replicas"]
                if all((reps[n] or {}).get("boot")
                       for n in ("r0", "r1")):
                    break
                router.step()
                time.sleep(0.01)
            assert reps["r0"]["boot"]["mode"] == "aot"
            assert reps["r1"]["boot"]["mode"] == "traced"
        finally:
            router.close()
            e0.close()
            e1.close()

    def test_stale_fingerprint_after_config_change_reexports(
            self, gpt_model, gpt_store, tmp_path):
        """Config changed under a warm store: the next boot refuses
        the old artifact (stale_fingerprint), serves traced, and
        republishes under the NEW fingerprint — after which the
        changed config boots aot too."""
        root = _copy_store(gpt_store["root"], tmp_path / "store")
        kw = dict(steps_per_dispatch=2)
        eng = _engine(gpt_model, **kw)
        info = warm_boot(eng, buckets=LENS, artifact_dir=root)
        assert info["mode"] == "traced"
        assert _counter(eng.registry, "serve_aot_fallback_total",
                        {"reason": "stale_fingerprint"}) == 1
        # dispatch schedule never changes tokens — the re-traced boot
        # still serves the same streams
        assert eng.generate(gpt_store["prompts"],
                            max_new_tokens=NEW_TOK) \
            == gpt_store["refs"]
        assert info["artifact"] is not None
        b = _engine(gpt_model, **kw)
        assert warm_boot(b, buckets=LENS,
                         artifact_dir=root)["mode"] == "aot"
        eng.close()
        b.close()


# -- surfaces: fleet_top BOOT column ----------------------------------------

class TestFleetTopBootColumn:
    def test_render_boot_column(self):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        import importlib
        ft = importlib.import_module("fleet_top")
        frame = {"ts": 0, "source": "test", "rates": {}, "health": {
            "replicas": {
                "r0": {"state": "serving", "incarnation": 1,
                       "boot": {"mode": "aot", "boot_s": 3.21,
                                "artifact": "art-x-1"}},
                "r1": {"state": "serving", "incarnation": 2,
                       "boot": {"mode": "traced", "boot_s": 9.87,
                                "artifact": None}},
                # pre-artifact replica: no boot payload at all
                "r2": {"state": "serving", "incarnation": 1}}}}
        text = ft.render(frame)
        assert "BOOT" in text
        assert "aot 3.2s" in text
        assert "traced 9.9s" in text
