"""Detection zoo: PP-YOLOE + DETR (ref: PaddleDetection test suite shape —
forward shapes, assigner/matcher correctness, one train step improves the
loss). All static shapes, CPU."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.hapi.engine import Engine
from paddle_tpu.vision.models.detection import (
    PPYOLOE, PPYOLOECriterion, DETR, DETRLoss, auction_match,
    task_aligned_assign, multiclass_nms, pairwise_iou)


def _gt(batch=1):
    gt_boxes = paddle.to_tensor(np.tile(np.array(
        [[[4, 4, 30, 30], [20, 10, 60, 50], [0, 0, 0, 0]]], np.float32),
        (batch, 1, 1)))
    gt_class = paddle.to_tensor(np.tile(
        np.array([[1, 2, 0]], np.int64), (batch, 1)))
    gt_mask = paddle.to_tensor(np.tile(
        np.array([[1, 1, 0]], np.float32), (batch, 1)))
    return gt_boxes, gt_class, gt_mask


class TestPPYOLOE:
    def _model(self):
        paddle.seed(0)
        return PPYOLOE(num_classes=4, channels=(8, 16, 24, 32, 40))

    def test_forward_shapes(self):
        from tests.conftest import jit_forward
        m = self._model()
        m.eval()
        x = np.random.RandomState(0).randn(2, 3, 64, 64).astype("float32")
        boxes, scores = jit_forward(m, jnp.asarray(x))
        a = 8 * 8 + 4 * 4 + 2 * 2  # strides 8/16/32 on 64px
        assert list(boxes.shape) == [2, a, 4]
        assert list(scores.shape) == [2, a, 4]

    def test_train_step_improves_loss(self):
        m = self._model()
        m.train()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters())
        eng = Engine(m, loss=PPYOLOECriterion(m), optimizer=opt)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(1, 3, 64, 64).astype("float32"))
        labels = _gt()
        losses = [float(eng.train_batch([x], list(labels))[0])
                  for _ in range(3)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_tal_assigner_prefers_high_iou_anchor(self):
        a = 16
        anchors = jnp.stack(
            [jnp.linspace(4, 60, a), jnp.full((a,), 16.0)], -1)
        boxes = jnp.stack([anchors[:, 0] - 8, anchors[:, 1] - 8,
                           anchors[:, 0] + 8, anchors[:, 1] + 8], -1)
        gt = jnp.asarray([[0.0, 8.0, 16.0, 24.0]])  # matches anchor near x=8
        scores = jnp.full((a, 3), 0.5)
        assigned, fg, tscore = task_aligned_assign(
            scores, boxes, anchors, gt, jnp.asarray([1]), jnp.asarray([1.0]),
            topk=4)
        fg_idx = np.where(np.asarray(fg))[0]
        assert len(fg_idx) > 0
        iou, _ = pairwise_iou(boxes, gt)
        assert np.asarray(iou)[fg_idx, 0].min() > 0.2
        assert np.asarray(tscore)[fg_idx, 1].min() > 0.0
        assert np.asarray(tscore)[:, [0, 2]].max() == 0.0

    def test_multiclass_nms_suppresses_overlaps(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                         np.float32)
        scores = np.zeros((3, 2), np.float32)
        scores[:, 1] = [0.9, 0.8, 0.7]
        dets = multiclass_nms(boxes, scores, score_thresh=0.1,
                              iou_thresh=0.5)
        assert len(dets) == 2  # the two overlapping boxes collapse to one


class TestDETR:
    def _model(self):
        paddle.seed(0)
        return DETR(num_classes=4, num_queries=10, d_model=32, nhead=2,
                    num_encoder_layers=1, num_decoder_layers=1,
                    dim_feedforward=64, backbone="tiny", dropout=0.0)

    def test_forward_shapes(self):
        from tests.conftest import jit_forward
        m = self._model()
        m.eval()
        x = np.random.RandomState(0).randn(2, 3, 64, 64).astype("float32")
        boxes, probs = jit_forward(m, jnp.asarray(x))
        assert list(boxes.shape) == [2, 10, 4]
        assert list(probs.shape) == [2, 10, 5]  # +1 no-object class
        # boxes are in pixel space
        assert float(boxes.max()) <= 64.0 + 1e-3

    def test_train_step_improves_loss(self):
        m = self._model()
        m.train()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=m.parameters())
        eng = Engine(m, loss=DETRLoss(num_classes=4), optimizer=opt)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(1, 3, 64, 64).astype("float32"))
        gt_boxes = paddle.to_tensor(np.array(
            [[[.3, .3, .2, .2], [.6, .6, .3, .3], [0, 0, 0, 0]]],
            np.float32))
        gt_class = paddle.to_tensor(np.array([[1, 2, 0]], np.int64))
        gt_mask = paddle.to_tensor(np.array([[1, 1, 0]], np.float32))
        losses = [float(eng.train_batch([x],
                                        [gt_boxes, gt_class, gt_mask])[0])
                  for _ in range(3)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]


class TestAuctionMatch:
    def test_matches_scipy_optimum(self):
        scipy_opt = pytest.importorskip(
            "scipy.optimize", reason="environmental gate: scipy is an "
            "optional dependency — linear_sum_assignment is only the "
            "REFERENCE optimum the auction matcher is checked against")
        rng = np.random.default_rng(0)
        for trial in range(10):
            q, m = 16, 5
            cost = rng.normal(size=(q, m)).astype("float32")
            valid = np.ones(m, bool)
            if trial % 2:
                valid[3:] = False
            match = np.asarray(auction_match(jnp.asarray(cost),
                                             jnp.asarray(valid)))
            # distinct queries for valid gts
            assert len(set(match[valid])) == valid.sum()
            r, c = scipy_opt.linear_sum_assignment(cost[:, valid].T)
            opt = cost[:, valid].T[r, c].sum()
            got = cost[match[valid], np.arange(m)[valid]].sum()
            assert abs(got - opt) < 0.05
