"""paddle.vision.ops: nms vs naive greedy reference, roi ops invariants,
deform_conv2d degenerate == regular conv, box_coder round trip."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


def naive_nms(boxes, scores, thr):
    """reference greedy NMS."""
    order = np.argsort(-scores)
    keep = []
    alive = np.ones(len(boxes), bool)
    for j in order:
        if not alive[j]:
            continue
        keep.append(j)
        for k in order:
            if alive[k] and k != j:
                # iou
                lt = np.maximum(boxes[j, :2], boxes[k, :2])
                rb = np.minimum(boxes[j, 2:], boxes[k, 2:])
                wh = np.clip(rb - lt, 0, None)
                inter = wh[0] * wh[1]
                a1 = np.prod(np.clip(boxes[j, 2:] - boxes[j, :2], 0, None))
                a2 = np.prod(np.clip(boxes[k, 2:] - boxes[k, :2], 0, None))
                if inter / (a1 + a2 - inter + 1e-9) > thr:
                    alive[k] = False
    return np.array(keep)


class TestNMS:
    def test_vs_naive(self):
        rng = np.random.default_rng(0)
        for trial in range(5):
            xy = rng.uniform(0, 50, (40, 2)).astype(np.float32)
            wh = rng.uniform(5, 25, (40, 2)).astype(np.float32)
            boxes = np.concatenate([xy, xy + wh], -1)
            scores = rng.uniform(0, 1, 40).astype(np.float32)
            got = _np(V.nms(paddle.to_tensor(boxes), 0.4,
                            scores=paddle.to_tensor(scores)))
            ref = naive_nms(boxes, scores, 0.4)
            assert np.array_equal(np.sort(got), np.sort(ref)), trial
            # sorted by score
            assert np.all(np.diff(scores[got]) <= 1e-6)

    def test_no_scores_uses_input_order(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [30, 30, 40, 40]],
                         np.float32)
        got = _np(V.nms(paddle.to_tensor(boxes), 0.3))
        assert np.array_equal(np.sort(got), [0, 2])

    def test_categories(self):
        # same box, different category: both kept
        boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        got = _np(V.nms(paddle.to_tensor(boxes), 0.3,
                        scores=paddle.to_tensor(scores),
                        category_idxs=paddle.to_tensor(
                            np.array([0, 1], np.int64)),
                        categories=[0, 1]))
        assert len(got) == 2

    def test_top_k_fixed_shape(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [30, 30, 40, 40]],
                         np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        got = _np(V.nms(paddle.to_tensor(boxes), 0.3,
                        scores=paddle.to_tensor(scores), top_k=3))
        assert got.shape == (3,)
        assert got[0] == 0 and got[1] == 2 and got[2] == -1

    def test_box_iou(self):
        a = np.array([[0, 0, 10, 10]], np.float32)
        b = np.array([[0, 0, 10, 10], [5, 5, 15, 15], [20, 20, 30, 30]],
                     np.float32)
        iou = _np(V.box_iou(paddle.to_tensor(a), paddle.to_tensor(b)))
        assert np.allclose(iou, [[1.0, 25 / 175, 0.0]], atol=1e-5)


class TestRoiOps:
    def test_roi_align_constant_feature(self):
        x = np.full((1, 3, 16, 16), 7.0, np.float32)
        boxes = np.array([[2, 2, 10, 10], [0, 0, 15, 15]], np.float32)
        out = V.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                          paddle.to_tensor(np.array([2], np.int32)), 4)
        o = _np(out)
        assert o.shape == (2, 3, 4, 4)
        assert np.allclose(o, 7.0, atol=1e-5)

    def test_roi_align_linear_gradient_field(self):
        # f(y, x) = x: averaged over a bin = bin center x
        x = np.broadcast_to(np.arange(32, dtype=np.float32)[None, None, None, :],
                            (1, 1, 32, 32)).copy()
        boxes = np.array([[4, 4, 12, 12]], np.float32)
        out = _np(V.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                              paddle.to_tensor(np.array([1], np.int32)),
                              2, aligned=False))
        # unaligned convention: bins are x in [4,8] and [8,12]; bilinear
        # samples of a linear field average to the bin centers 6 and 10
        assert out.shape == (1, 1, 2, 2)
        assert np.allclose(out[0, 0, 0, 0], 6.0, atol=0.05)
        assert np.allclose(out[0, 0, 0, 1], 10.0, atol=0.05)
        assert np.allclose(out[0, 0, 0], out[0, 0, 1], atol=1e-5)

    def test_roi_pool_max(self):
        x = np.zeros((1, 1, 8, 8), np.float32)
        x[0, 0, 3, 3] = 5.0
        boxes = np.array([[0, 0, 8, 8]], np.float32)
        out = _np(V.roi_pool(paddle.to_tensor(x), paddle.to_tensor(boxes),
                             paddle.to_tensor(np.array([1], np.int32)), 2))
        assert out.shape == (1, 1, 2, 2)
        # exact max semantics: the 5.0 peak pixel is in bin (0, 0)
        assert np.allclose(out[0, 0], [[5.0, 0.0], [0.0, 0.0]])

    def test_distribute_fpn(self):
        rois = np.array([
            [0, 0, 10, 10],      # small -> low level
            [0, 0, 500, 500],    # big  -> high level
        ], np.float32)
        lvl, masks = V.distribute_fpn_proposals(
            paddle.to_tensor(rois), 2, 5, 4, 224)
        lv = _np(lvl)
        m = _np(masks)
        assert lv[0] == 2 and lv[1] == 5
        assert m.shape == (4, 2)
        assert m[0, 0] == 1 and m[3, 1] == 1


class TestDeformConv:
    def test_zero_offset_equals_conv(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 4, 9, 9)).astype(np.float32)
        w = rng.standard_normal((6, 4, 3, 3)).astype(np.float32) * 0.2
        off = np.zeros((2, 2 * 9, 7, 7), np.float32)
        got = _np(V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                                  paddle.to_tensor(w)))
        ref = _np(F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w)))
        assert got.shape == ref.shape
        assert np.allclose(got, ref, atol=1e-4)

    def test_mask_scales(self):
        rng = np.random.default_rng(2)
        x = rng.standard_normal((1, 2, 7, 7)).astype(np.float32)
        w = rng.standard_normal((3, 2, 3, 3)).astype(np.float32) * 0.2
        off = np.zeros((1, 18, 5, 5), np.float32)
        mask_half = np.full((1, 9, 5, 5), 0.5, np.float32)
        full = _np(V.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(off),
                                   paddle.to_tensor(w)))
        halfd = _np(V.deform_conv2d(paddle.to_tensor(x),
                                    paddle.to_tensor(off),
                                    paddle.to_tensor(w),
                                    mask=paddle.to_tensor(mask_half)))
        assert np.allclose(halfd, full * 0.5, atol=1e-4)

    def test_layer_trains(self):
        layer = V.DeformConv2D(2, 3, 3, padding=1)
        x = paddle.to_tensor(
            np.random.default_rng(3).standard_normal((1, 2, 6, 6))
            .astype(np.float32))
        off = paddle.to_tensor(np.zeros((1, 18, 6, 6), np.float32))
        out = layer(x, off)
        assert tuple(out.shape) == (1, 3, 6, 6)
        loss = (out ** 2).mean()
        loss.backward()
        assert layer.weight.grad is not None


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.default_rng(4)
        priors = np.array([[10, 10, 30, 30], [40, 40, 90, 100]], np.float32)
        var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
        targets = np.array([[12, 14, 33, 35], [45, 42, 80, 95]], np.float32)
        enc = _np(V.box_coder(paddle.to_tensor(priors), var,
                              paddle.to_tensor(targets),
                              code_type="encode_center_size"))
        # decode each target's own prior (diagonal of the N x M encoding)
        diag = np.stack([enc[i, i] for i in range(2)])[None]  # [1, M, 4]
        dec = _np(V.box_coder(paddle.to_tensor(priors), var,
                              paddle.to_tensor(diag.transpose(1, 0, 2)),
                              code_type="decode_center_size", axis=1))
        assert np.allclose(dec[:, 0, :], targets, atol=1e-3)

    def test_yolo_box_shapes(self):
        rng = np.random.default_rng(5)
        x = rng.standard_normal((2, 3 * 7, 4, 4)).astype(np.float32)
        boxes, scores = V.yolo_box(
            paddle.to_tensor(x),
            paddle.to_tensor(np.array([[64, 64], [64, 64]], np.int32)),
            anchors=[10, 13, 16, 30, 33, 23], class_num=2,
            conf_thresh=0.01, downsample_ratio=16)
        assert tuple(boxes.shape) == (2, 48, 4)
        assert tuple(scores.shape) == (2, 48, 2)
        b = _np(boxes)
        assert b.min() >= 0 and b.max() <= 63.001

    def test_yolo_box_iou_aware(self):
        # regression: iou channels were silently ignored. Layout: na iou
        # channels first, then na*(5+C)
        rng = np.random.default_rng(6)
        na, C = 3, 2
        x = rng.standard_normal((1, na + na * (5 + C), 4, 4)) \
            .astype(np.float32)
        img = paddle.to_tensor(np.array([[64, 64]], np.int32))
        kw = dict(anchors=[10, 13, 16, 30, 33, 23], class_num=C,
                  conf_thresh=-1.0, downsample_ratio=16)
        _, s_aware = V.yolo_box(paddle.to_tensor(x), img, iou_aware=True,
                                iou_aware_factor=0.5, **kw)
        # reference: conf = obj^(1-f) * iou^f * cls
        def sig(v):
            return 1 / (1 + np.exp(-v))
        v = x[:, na:].reshape(1, na, 5 + C, 4, 4)
        iou = sig(x[:, :na].reshape(1, na, 4, 4))
        obj = sig(v[:, :, 4]) ** 0.5 * iou ** 0.5
        ref = (obj[:, :, None] * sig(v[:, :, 5:])).transpose(0, 1, 3, 4, 2)
        assert np.allclose(_np(s_aware), ref.reshape(1, -1, C), atol=1e-4)

    def test_psroi_pool(self):
        # constant per channel-group: output bin (i, j) must read group
        # value c*oh*ow + i*ow + j
        oh = ow = 2
        c_out = 3
        x = np.zeros((1, c_out * oh * ow, 8, 8), np.float32)
        for c in range(c_out):
            for i in range(oh):
                for j in range(ow):
                    x[0, c * oh * ow + i * ow + j] = c * 100 + i * 10 + j
        boxes = np.array([[0, 0, 8, 8]], np.float32)
        layer = V.PSRoIPool(2)
        out = _np(layer(paddle.to_tensor(x), paddle.to_tensor(boxes),
                        paddle.to_tensor(np.array([1], np.int32))))
        assert out.shape == (1, c_out, 2, 2)
        for c in range(c_out):
            for i in range(oh):
                for j in range(ow):
                    assert np.allclose(out[0, c, i, j], c * 100 + i * 10 + j)
