"""scan_layers=True (stacked [L,...] params + one lax.scan) must match
the unrolled decoder exactly, shrink the traced program, and fail loudly
on the paths it does not cover (KV-cache decode, eager-tape training).

ref parity: the reference trains GPT-3 1.3B through fleet recompute over
unrolled CUDA blocks; scan-over-layers is the XLA-idiom equivalent
(gpt.py ScannedGPTLayers)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.hapi.engine import Engine
from paddle_tpu.nlp.gpt import (GPTConfig, GPTForCausalLM,
                                GPTPretrainingCriterion, stack_layer_state,
                                unstack_layer_state)
from paddle_tpu.optimizer import AdamW
from paddle_tpu.tensor import Tensor

CFG = dict(vocab_size=89, hidden_size=32, num_hidden_layers=4,
           num_attention_heads=4, max_position_embeddings=32,
           hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
           use_flash_attention=False)


def _models():
    paddle.seed(7)
    unrolled = GPTForCausalLM(GPTConfig(**CFG))
    scanned = GPTForCausalLM(GPTConfig(**CFG, scan_layers=True))
    sd = stack_layer_state(unrolled.state_dict(), CFG["num_hidden_layers"],
                           prefix="gpt.h.")
    # COPY the leaves: set_state_dict shares arrays, and the Engine
    # donates its params — a shared buffer would be deleted under the
    # other model after its first step
    sd = {k: jnp.array(np.asarray(v._value if isinstance(v, Tensor) else v))
          for k, v in sd.items()}
    scanned.set_state_dict(sd)
    return unrolled, scanned


def _engine(model, sgd=False):
    model.train()
    # SGD for lockstep param comparisons: it is linear in the gradient,
    # so scan-vs-unrolled fp32 reassociation noise stays O(1e-7); Adam
    # divides near-zero moments and amplifies that noise arbitrarily
    if sgd:
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=model.parameters())
    else:
        opt = AdamW(learning_rate=1e-3, weight_decay=0.01,
                    parameters=model.parameters())
    return Engine(model, loss=GPTPretrainingCriterion(), optimizer=opt)


def _data(b=2, s=16, steps=3):
    rng = np.random.default_rng(0)
    return [(jnp.asarray(rng.integers(0, CFG["vocab_size"], (b, s)),
                         jnp.int32),
             jnp.asarray(rng.integers(0, CFG["vocab_size"], (b, s)),
                         jnp.int32)) for _ in range(steps)]


def test_scanned_training_matches_unrolled_exactly():
    unrolled, scanned = _models()
    eu, es = _engine(unrolled, sgd=True), _engine(scanned, sgd=True)
    for ids, labels in _data():
        lu, _ = eu.train_batch([ids], [labels])
        ls, _ = es.train_batch([ids], [labels])
        np.testing.assert_allclose(float(lu), float(ls), rtol=1e-6)
    # parameters stay in lockstep after 3 optimizer steps
    su = stack_layer_state(unrolled.state_dict(),
                           CFG["num_hidden_layers"], prefix="gpt.h.")
    ss = scanned.state_dict()
    for k, v in ss.items():
        np.testing.assert_allclose(
            np.asarray(su[k]._value if isinstance(su[k], Tensor)
                       else su[k]),
            np.asarray(v._value), rtol=2e-5, atol=2e-6, err_msg=k)


def test_scanned_recompute_matches_no_recompute():
    _, scanned = _models()
    paddle.seed(7)
    remat = GPTForCausalLM(GPTConfig(**CFG, scan_layers=True,
                                     recompute=True))
    remat.set_state_dict({  # copy: donation would delete shared buffers
        k: jnp.array(np.asarray(v._value))
        for k, v in scanned.state_dict().items()})
    e1, e2 = _engine(scanned), _engine(remat)
    for ids, labels in _data(steps=2):
        l1, _ = e1.train_batch([ids], [labels])
        l2, _ = e2.train_batch([ids], [labels])
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)


def test_stack_unstack_roundtrip():
    unrolled, _ = _models()
    sd = {k: np.asarray(v._value) for k, v in unrolled.state_dict().items()}
    stacked = stack_layer_state(sd, CFG["num_hidden_layers"],
                                prefix="gpt.h.")
    back = unstack_layer_state(stacked, CFG["num_hidden_layers"],
                               prefix="gpt.h.")
    assert set(back) == set(sd)
    for k in sd:
        np.testing.assert_array_equal(back[k], sd[k])


def test_dropout_path_runs_and_perturbs():
    """Dropout inside the scan must actually drop (train != eval) and
    per-layer keys must ride the scan xs: with all layers' weights
    IDENTICAL and a 2-layer residual-free probe this is hard to observe
    directly, so assert the observable contract — train-mode losses
    vary across steps under fixed inputs (fresh masks each step) and
    differ from the deterministic eval loss."""
    paddle.seed(3)
    m = GPTForCausalLM(GPTConfig(**{**CFG, "hidden_dropout_prob": 0.5},
                                 scan_layers=True))
    crit = GPTPretrainingCriterion()
    ids, labels = _data(steps=1)[0]

    from paddle_tpu.nn.layer import functional_call
    params, buffers = m.raw_state()

    def loss_with(seed_key, training):
        from paddle_tpu.autograd import no_grad
        m.train() if training else m.eval()
        with no_grad():  # forward-only probe; eager train fwd is allowed
            out = functional_call(m, params, buffers, Tensor(ids),
                                  rng=jax.random.PRNGKey(seed_key))
        logits = out[0] if isinstance(out, tuple) else out
        return float(crit(logits, Tensor(labels))._value)

    l_train_a = loss_with(0, True)
    l_train_b = loss_with(1, True)
    l_eval = loss_with(0, False)
    assert np.isfinite([l_train_a, l_train_b, l_eval]).all()
    assert l_train_a != l_train_b, "different rng keys gave same masks"
    assert l_train_a != l_eval, "train-mode dropout was a no-op"


def test_cache_decode_raises():
    _, scanned = _models()
    scanned.eval()
    ids = Tensor(jnp.asarray([[1, 2, 3]], jnp.int32))
    with pytest.raises(NotImplementedError, match="scan_layers"):
        scanned(ids, use_cache=True)


def test_eager_training_raises():
    _, scanned = _models()
    scanned.train()
    ids = Tensor(jnp.asarray([[1, 2, 3]], jnp.int32))
    with pytest.raises(RuntimeError, match="eager"):
        scanned(ids)


def test_program_size_shrinks():
    from paddle_tpu import jit as pjit
    unrolled, scanned = _models()
    unrolled.eval(), scanned.eval()
    ids = jnp.zeros((1, 8), jnp.int32)

    def loss_of(model):
        params, buffers = model.raw_state()

        def f(p, i):
            from paddle_tpu.nn.layer import functional_call
            out = functional_call(model, p, buffers, Tensor(i))
            logits = out[0] if isinstance(out, tuple) else out
            v = logits._value if isinstance(logits, Tensor) else logits
            return jnp.sum(v)
        return f, params

    fu, pu = loss_of(unrolled)
    fs, ps = loss_of(scanned)
    hlo_u = pjit.get_hlo(fu, pu, ids)
    hlo_s = pjit.get_hlo(fs, ps, ids)
    # 4 unrolled layers vs one scanned body: the traced program must
    # shrink markedly (the point of the lever at 24 layers/1.3B). The
    # ratio at L=4 depends on the jax version's StableHLO printer
    # boilerplate (0.58 on the r5 box, 0.65 on this one's jax 0.4.37)
    # — 0.75 keeps the invariant meaningful without pinning a printer.
    assert len(hlo_s) < 0.75 * len(hlo_u), (len(hlo_s), len(hlo_u))


def test_bert_ernie_scanned_forward_matches_unrolled():
    """The generic ScannedLayerStack behind BertConfig.scan_layers must
    reproduce the unrolled encoder bit-for-bit at eval (BERT + ERNIE)."""
    from paddle_tpu.autograd import no_grad
    from paddle_tpu.nlp.bert import BertConfig, BertModel
    from paddle_tpu.nlp.ernie import ErnieConfig, ErnieModel
    from paddle_tpu.nn.scan_stack import stack_layer_state

    for Model, Config in ((BertModel, BertConfig), (ErnieModel, ErnieConfig)):
        cfg = dict(vocab_size=67, hidden_size=32, num_hidden_layers=3,
                   num_attention_heads=4, max_position_embeddings=32,
                   hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                   use_flash_attention=False)
        paddle.seed(5)
        unrolled = Model(Config(**cfg))
        scanned = Model(Config(**cfg, scan_layers=True))
        sd = stack_layer_state(
            {k: np.asarray(v._value)
             for k, v in unrolled.state_dict().items()},
            cfg["num_hidden_layers"], prefix="encoder.")
        scanned.set_state_dict(sd)
        unrolled.eval(), scanned.eval()
        ids = jnp.asarray(np.random.default_rng(1).integers(
            0, 67, (2, 12)), jnp.int32)
        with no_grad():
            seq_u, pool_u = unrolled(Tensor(ids))
            seq_s, pool_s = scanned(Tensor(ids))
        np.testing.assert_allclose(np.asarray(seq_u._value),
                                   np.asarray(seq_s._value),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=Model.__name__)
        np.testing.assert_allclose(np.asarray(pool_u._value),
                                   np.asarray(pool_s._value),
                                   rtol=1e-5, atol=1e-6)
