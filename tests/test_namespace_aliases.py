"""Top-level namespace aliases from the round-2 completeness sweep."""
import numpy as np

import paddle_tpu as paddle

t = paddle.to_tensor


def _np(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


class TestAliases:
    def test_all_any(self):
        assert bool(_np(paddle.all(t(np.array([True, True])))))
        assert not bool(_np(paddle.all(t(np.array([True, False])))))
        assert bool(_np(paddle.any(t(np.array([False, True])))))
        m = t(np.array([[True, False], [True, True]]))
        assert list(_np(paddle.all(m, axis=0))) == [True, False]
        assert _np(paddle.any(m, axis=1, keepdim=True)).shape == (2, 1)

    def test_linalg_aliases(self):
        x = t(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        assert np.allclose(_np(paddle.inverse(x)) @ _np(x), np.eye(2),
                           atol=1e-5)
        assert np.allclose(_np(paddle.mm(x, x)), _np(x) @ _np(x))
        assert np.allclose(_np(paddle.mv(x, t(np.array([1.0, 1.0],
                                                       np.float32)))),
                           [3.0, 7.0])
        assert float(_np(paddle.norm(x))) > 0
        assert float(_np(paddle.cond(x))) > 0

    def test_shape_introspection(self):
        x = t(np.zeros((2, 3), np.float32))
        assert int(_np(paddle.numel(x))) == 6
        assert int(_np(paddle.rank(x))) == 2
        assert list(_np(paddle.shape(x))) == [2, 3]

    def test_cat(self):
        x = t(np.ones((2, 2), np.float32))
        assert tuple(paddle.cat([x, x]).shape) == (4, 2)
        assert tuple(paddle.cat([x, x], axis=1).shape) == (2, 4)
