"""Auto-parallel planner: spec proposal, cost model, placement, GSPMD
numerics (SURVEY §2.6)."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel import (
    apply_plan, estimate_cost, parallelize, plan_model, Strategy)
from paddle_tpu.tensor import Tensor


def _mesh():
    return Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "mp"))


def _mlp():
    paddle.seed(0)
    return paddle.nn.Sequential(
        paddle.nn.Linear(16, 64), paddle.nn.GELU(),
        paddle.nn.Linear(64, 16))


def test_plan_alternates_column_row():
    plan = plan_model(_mlp(), _mesh(), Strategy(min_shard_elems=1))
    specs = [tuple(v) for k, v in plan.items() if k.endswith("weight")]
    assert ("mp",) not in specs  # weights are 2D
    assert (None, "mp") in specs and ("mp", None) in specs


def test_plan_respects_existing_mpu_specs():
    from paddle_tpu.distributed.fleet.mpu import ColumnParallelLinear
    m = ColumnParallelLinear(8, 32)
    plan = plan_model(m, _mesh())
    assert tuple(plan["weight"]) == (None, "mp")


def test_cost_model_prefers_sharded():
    mesh = _mesh()
    assert estimate_cost((64, 64), P(None, "mp"), mesh) \
        < estimate_cost((64, 64), P(), mesh)


def test_apply_plan_places_params():
    mesh = _mesh()
    m = _mlp()
    plan = plan_model(m, mesh, Strategy(min_shard_elems=1))
    apply_plan(m, plan, mesh)
    w0 = m[0].weight
    assert isinstance(w0._value.sharding, NamedSharding)
    assert tuple(w0._value.sharding.spec) == tuple(plan["0.weight"])


def test_parallelized_forward_matches_dense():
    mesh = _mesh()
    m = _mlp()
    m.eval()
    x = Tensor(jnp.asarray(
        np.random.default_rng(0).standard_normal((8, 16)), jnp.float32))
    want = np.asarray(m(x)._value)
    parallelize(m, mesh=mesh, strategy=Strategy(min_shard_elems=1))
    got = np.asarray(m(x)._value)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_parallelized_training_matches_dense():
    from paddle_tpu.hapi.engine import Engine
    mesh = _mesh()
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)
    Y = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)

    def run(auto):
        m = _mlp()
        opt = paddle.optimizer.Adam(1e-2, parameters=m.parameters())
        if auto:
            parallelize(m, mesh=mesh, strategy=Strategy(min_shard_elems=1))
        eng = Engine(m, loss=paddle.nn.MSELoss(), optimizer=opt,
                     mesh=mesh if auto else None)
        return [float(eng.train_batch([X], [Y])[0]) for _ in range(5)]

    np.testing.assert_allclose(run(True), run(False), rtol=1e-4, atol=1e-5)
