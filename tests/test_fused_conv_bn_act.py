"""Pallas fused 1x1-conv+BN+ReLU(+residual) kernel: interpret-mode
parity vs the jnp reference (SURVEY §4 pallas test strategy), gradients
through the custom vjp, the non-tiling fallback, the Gram-trick batch
stats, and the BottleneckBlock integration (fused == plain through
eval AND train incl. running-stat updates)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.conv_bn_act import (_reference,
                                               conv1x1_batch_stats,
                                               fused_conv1x1_bn_act)


def _inputs(m=64, cin=128, cout=256, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    return (jax.random.normal(ks[0], (m, cin), dtype),
            jax.random.normal(ks[1], (cin, cout), dtype) * 0.05,
            jax.random.normal(ks[2], (cout,), jnp.float32) * 0.1 + 1.0,
            jax.random.normal(ks[3], (cout,), jnp.float32) * 0.1,
            jax.random.normal(ks[4], (m, cout), dtype))


@pytest.mark.parametrize("dtype,atol", [(jnp.float32, 1e-4),
                                        (jnp.bfloat16, 5e-2)])
@pytest.mark.parametrize("with_res,relu", [(True, True), (False, True),
                                           (True, False)])
def test_forward_parity(dtype, atol, with_res, relu):
    x, w, s, b, r = _inputs(dtype=dtype)
    res = r if with_res else None
    y = fused_conv1x1_bn_act(x, w, s, b, res, relu, 0, True)
    yr = _reference(x, w, s, b, res, relu)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(yr, np.float32), atol=atol)


def test_grads_parity():
    x, w, s, b, r = _inputs()
    c = jax.random.normal(jax.random.PRNGKey(9), (x.shape[0], w.shape[1]))

    def loss_fused(x, w, s, b, r):
        return jnp.sum(fused_conv1x1_bn_act(x, w, s, b, r, True, 0, True)
                       * c)

    def loss_ref(x, w, s, b, r):
        return jnp.sum(_reference(x, w, s, b, r, True) * c)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(x, w, s, b, r)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(x, w, s, b, r)
    for a, bb, name in zip(gf, gr, "x w scale shift res".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   atol=5e-3, rtol=5e-4, err_msg=name)


def test_non_tiling_channels_fall_back():
    # cin=96 is not a lane multiple — must still be exact via the
    # reference fallback (layer1's 64-channel convs take this path)
    x, w, s, b, r = _inputs(m=40, cin=96, cout=128)
    y = fused_conv1x1_bn_act(x, w, s, b, r, True, 0, True)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(_reference(x, w, s, b, r, True)),
                               atol=1e-5)


def test_gram_batch_stats_match_direct():
    x, w, *_ = _inputs(m=256, cin=128, cout=256)
    mean, var = conv1x1_batch_stats(x, w)
    xw = x @ w
    np.testing.assert_allclose(np.asarray(mean), np.asarray(xw.mean(0)),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(var), np.asarray(xw.var(0)),
                               atol=1e-3, rtol=1e-3)


# ---------------------------------------------------------------------------
# BottleneckBlock integration
# ---------------------------------------------------------------------------

def _blocks(seed=3, inplanes=256, planes=64):
    from paddle_tpu.nn.layers_conv import to_channels_last
    from paddle_tpu.vision.models.resnet import BottleneckBlock
    paddle.seed(seed)
    plain = BottleneckBlock(inplanes, planes)
    paddle.seed(seed)
    fused = BottleneckBlock(inplanes, planes)
    to_channels_last(fused)
    fused._fused = True
    return plain, fused


def _x(shape=(2, 8, 8, 256), seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape), jnp.float32)


@pytest.mark.parametrize("training", [False, True])
def test_bottleneck_block_fused_parity(training):
    plain, fused = _blocks()
    x = _x()
    xn = jnp.transpose(x, (0, 3, 1, 2))
    (plain.train() if training else plain.eval())
    (fused.train() if training else fused.eval())
    a = plain(paddle.Tensor(xn))
    b = fused(paddle.Tensor(x))
    np.testing.assert_allclose(
        np.asarray(a._value),
        np.asarray(b.transpose([0, 3, 1, 2])._value), atol=5e-4)
    if training:
        # the Gram-trick batch stats must drive the SAME running-stat
        # update as the materialized conv output (F.batch_norm parity)
        np.testing.assert_allclose(
            np.asarray(plain.bn3._mean._value),
            np.asarray(fused.bn3._mean._value), atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(plain.bn3._variance._value),
            np.asarray(fused.bn3._variance._value), atol=1e-5,
            rtol=1e-4)


def test_bottleneck_block_fused_grads():
    from paddle_tpu.nn.layer import functional_call
    plain, fused = _blocks()
    x = _x()
    xn = jnp.transpose(x, (0, 3, 1, 2))
    plain.train()
    fused.train()

    def grads(m, inp):
        params, buffers = m.raw_state()

        @jax.jit
        def g(p, b, a):
            def loss_fn(pp):
                out = functional_call(m, pp, b, paddle.Tensor(a))
                return jnp.sum(jnp.square(out._value))
            return jax.grad(loss_fn)(p)
        return g(params, buffers, inp)

    g1 = grads(plain, xn)
    g2 = grads(fused, x)
    for k in ("conv1.weight", "conv3.weight", "bn1.weight", "bn3.weight",
              "bn3.bias"):
        a, b = np.asarray(g1[k]), np.asarray(g2[k])
        if a.ndim == 4:
            a = a.transpose(2, 3, 1, 0)
        scale = max(1.0, np.abs(a).max())
        np.testing.assert_allclose(a / scale, b / scale, atol=2e-5,
                                   err_msg=k)


def test_fused_conv1x1_bn_guards():
    """The fused helper must decline (not crash) on shapes/configs it
    can't serve: NCHW weights, strided conv, contracting train-mode
    conv (Gram cost), and Identity bn after fuse_conv_bn."""
    from paddle_tpu.nn.layers_common import Identity
    from paddle_tpu.vision.models.resnet import _fused_conv1x1_bn
    from paddle_tpu import nn
    paddle.seed(0)
    conv = nn.Conv2D(64, 128, 1, bias_attr=False)
    bn = nn.BatchNorm2D(128)
    x = paddle.Tensor(_x((2, 4, 4, 64)))
    assert _fused_conv1x1_bn(x, conv, bn) is None  # NCHW weights
    conv.to_channels_last()
    bn.to_channels_last()
    assert _fused_conv1x1_bn(x, conv, bn) is not None
    assert _fused_conv1x1_bn(x, conv, Identity()) is None
    contracting = nn.Conv2D(128, 64, 1, bias_attr=False).to_channels_last()
    bn64 = nn.BatchNorm2D(64, data_format="NHWC")
    xc = paddle.Tensor(_x((2, 4, 4, 128), seed=1))
    # train-mode batch stats on a contracting 1x1 would cost more FLOPs
    # than the conv — declined; eval folds running stats and fuses
    assert _fused_conv1x1_bn(xc, contracting, bn64, training=True) is None
    assert _fused_conv1x1_bn(xc, contracting, bn64, training=False) \
        is not None


def test_bf16_grad_dtypes_match_primals():
    """custom_vjp checks cotangent avals against the primal dtypes —
    under TPU AMP every input is bf16 and the backward must not leak
    its internal fp32 accumulation into the returned cotangents
    (review catch: dres/dshift used the WRONG saved dtype and would
    crash the first --fused-bottleneck AMP grad step on hardware)."""
    x, w, s, b, r = _inputs(dtype=jnp.bfloat16)
    g = jax.grad(
        lambda *a: jnp.sum(
            fused_conv1x1_bn_act(*a, True, 0, True).astype(jnp.float32)),
        argnums=(0, 1, 2, 3, 4))(x, w, s, b, r)
    for got, prim, name in zip(g, (x, w, s, b, r),
                               "x w scale shift res".split()):
        assert got.dtype == prim.dtype, (name, got.dtype, prim.dtype)
