"""Small-API parity: dlpack, iinfo/finfo, text datasets, hub, onnx gate."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestDlpack:
    def test_roundtrip_with_numpy(self):
        from paddle_tpu.utils import from_dlpack, to_dlpack
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        back = from_dlpack(x)  # consumer-style from a Tensor-backed array
        assert np.allclose(np.asarray(back.numpy()), np.asarray(x.numpy()))
        cap = to_dlpack(x)
        assert cap is not None

    def test_from_torch(self):
        torch = pytest.importorskip(
            "torch", reason="environmental gate: torch-cpu (baked into "
            "the image) provides the producer side of the dlpack "
            "exchange under test")
        from paddle_tpu.utils import from_dlpack
        t = torch.arange(12, dtype=torch.float32).reshape(3, 4)
        out = from_dlpack(t)
        assert tuple(out.shape) == (3, 4)
        assert np.allclose(np.asarray(out.numpy()),
                           t.numpy())


class TestDtypeInfo:
    def test_iinfo(self):
        i = paddle.iinfo(paddle.int8)
        assert i.min == -128 and i.max == 127 and i.bits == 8
        i32 = paddle.iinfo("int32")
        assert i32.max == 2 ** 31 - 1

    def test_finfo(self):
        f = paddle.finfo(paddle.float32)
        assert f.bits == 32
        assert np.isclose(f.eps, np.finfo(np.float32).eps)
        bf = paddle.finfo(paddle.bfloat16)
        assert bf.bits == 16
        assert bf.max > 3e38


class TestTextDatasets:
    def test_conll05st_shape(self):
        from paddle_tpu.text import Conll05st
        d = Conll05st(mode="train", n_samples=20)
        x, pred, y = d[0]
        assert x.shape == y.shape
        assert 0 <= int(pred) < x.shape[0]
        assert len(d) == 20

    def test_movielens(self):
        from paddle_tpu.text import Movielens
        d = Movielens(n_samples=10)
        s = d[0]
        assert len(s) == 8
        assert s[5].shape == (18,)  # category vec
        assert isinstance(float(s[7]), float)

    def test_wmt16(self):
        from paddle_tpu.text import WMT16
        d = WMT16(n_samples=5)
        src, tin, tout = d[0]
        assert src.ndim == 1 and len(tin) == len(tout)


class TestHubOnnx:
    def test_hub_local(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny(scale=1):\n"
            "    'a tiny entrypoint'\n"
            "    return {'scale': scale}\n")
        from paddle_tpu import hub
        assert "tiny" in hub.list(str(tmp_path))
        assert "tiny entrypoint" in hub.help(str(tmp_path), "tiny")
        assert hub.load(str(tmp_path), "tiny", scale=3) == {"scale": 3}
        with pytest.raises(NotImplementedError):
            hub.load("any/repo", "m", source="github")

    def test_onnx_gate_points_to_jit_save(self):
        with pytest.raises(NotImplementedError, match="jit.save"):
            paddle.onnx.export(None, "model.onnx")
