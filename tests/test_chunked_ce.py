"""Fused chunked head+CE (GPTConfig.chunked_ce): the [N, vocab] logits
never materialize; loss and every gradient must equal the plain
head->ParallelCrossEntropy path."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.hapi.engine import Engine
from paddle_tpu.nlp.gpt import (GPTConfig, GPTForCausalLM,
                                GPTPretrainingCriterion)
from paddle_tpu.optimizer import AdamW

CFG = dict(vocab_size=151, hidden_size=32, num_hidden_layers=2,
           num_attention_heads=4, max_position_embeddings=32,
           hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
           use_flash_attention=False)


def _one_step(chunked):
    paddle.seed(13)
    m = GPTForCausalLM(GPTConfig(**CFG, chunked_ce=chunked))
    m.train()
    eng = Engine(m, loss=GPTPretrainingCriterion(),
                 optimizer=AdamW(learning_rate=1e-3,
                                 parameters=m.parameters()))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 151, (2, 24)), jnp.int32)
    loss, _ = eng.train_batch([ids], [ids])
    p = jax.tree_util.tree_leaves(eng._params)[0]
    return float(loss), np.asarray(p)


def test_chunked_ce_train_step_matches_plain():
    # chunk=16 does not divide N=48 — exercises the padded tail too
    base_loss, base_p = _one_step(0)
    for chunk in (16, 64):
        ch_loss, ch_p = _one_step(chunk)
        assert abs(base_loss - ch_loss) < 1e-4, (chunk, base_loss, ch_loss)
        np.testing.assert_allclose(ch_p, base_p, atol=2e-4, rtol=2e-4)


def test_chunked_ce_ignore_index_matches_plain():
    # -100-padded labels (the standard MLM/CLM convention) must
    # contribute exactly zero loss, like ParallelCrossEntropy
    def one(chunked):
        paddle.seed(17)
        m = GPTForCausalLM(GPTConfig(**CFG, chunked_ce=chunked))
        m.train()
        eng = Engine(m, loss=GPTPretrainingCriterion(),
                     optimizer=AdamW(learning_rate=1e-3,
                                     parameters=m.parameters()))
        rng = np.random.default_rng(1)
        ids = jnp.asarray(rng.integers(0, 151, (2, 24)), jnp.int32)
        labels = np.array(ids)  # writable copy
        labels[:, ::3] = -100
        loss, _ = eng.train_batch([ids], [jnp.asarray(labels)])
        return float(loss)

    assert abs(one(0) - one(16)) < 1e-4, (one(0), one(16))


def test_chunked_ce_pipe_refuses_loudly():
    import pytest
    from paddle_tpu.nlp.gpt import GPTForCausalLMPipe
    with pytest.raises(NotImplementedError, match="chunked_ce"):
        GPTForCausalLMPipe(GPTConfig(**CFG, chunked_ce=16))


def test_chunked_ce_eval_path_still_returns_logits():
    paddle.seed(5)
    m = GPTForCausalLM(GPTConfig(**CFG, chunked_ce=16))
    m.eval()
    out = m(jnp.ones((1, 8), jnp.int32))
    assert out.shape == [1, 8, 151]  # eval serves logits as usual
