"""Fused chunked head+CE (GPTConfig.chunked_ce): the [N, vocab] logits
never materialize; loss and every gradient must equal the plain
head->ParallelCrossEntropy path."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.hapi.engine import Engine
from paddle_tpu.nlp.gpt import (GPTConfig, GPTForCausalLM,
                                GPTPretrainingCriterion)
from paddle_tpu.optimizer import AdamW

CFG = dict(vocab_size=151, hidden_size=32, num_hidden_layers=2,
           num_attention_heads=4, max_position_embeddings=32,
           hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
           use_flash_attention=False)


def _steps(chunked, n_steps=2):
    """Run n_steps and return (losses, FULL param tree) — a single
    step over one leaf cannot see a frozen tied head (the stale-weight
    constant bug diverged only at step 2, in the embedding leaf)."""
    paddle.seed(13)
    m = GPTForCausalLM(GPTConfig(**CFG, chunked_ce=chunked))
    m.train()
    eng = Engine(m, loss=GPTPretrainingCriterion(),
                 optimizer=AdamW(learning_rate=1e-3,
                                 parameters=m.parameters()))
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(n_steps):
        ids = jnp.asarray(rng.integers(0, 151, (2, 24)), jnp.int32)
        loss, _ = eng.train_batch([ids], [ids])
        losses.append(float(loss))
    return losses, {k: np.asarray(v) for k, v in
                    zip(range(10**9),
                        jax.tree_util.tree_leaves(eng._params))}


def test_chunked_ce_train_step_matches_plain():
    # chunk=16 does not divide N=48 — exercises the padded tail too.
    # TWO steps + EVERY param leaf: grads must reach the tied
    # embedding through the fused head, not only the decoder
    base_losses, base_p = _steps(0)
    for chunk in (16, 64):
        ch_losses, ch_p = _steps(chunk)
        for a, b in zip(base_losses, ch_losses):
            assert abs(a - b) < 1e-4, (chunk, base_losses, ch_losses)
        for k in base_p:
            np.testing.assert_allclose(ch_p[k], base_p[k], atol=2e-4,
                                       rtol=2e-4, err_msg=f"leaf {k}")


def test_chunked_ce_ignore_index_matches_plain():
    # -100-padded labels (the standard MLM/CLM convention) must
    # contribute exactly zero loss, like ParallelCrossEntropy
    def one(chunked):
        paddle.seed(17)
        m = GPTForCausalLM(GPTConfig(**CFG, chunked_ce=chunked))
        m.train()
        eng = Engine(m, loss=GPTPretrainingCriterion(),
                     optimizer=AdamW(learning_rate=1e-3,
                                     parameters=m.parameters()))
        rng = np.random.default_rng(1)
        ids = jnp.asarray(rng.integers(0, 151, (2, 24)), jnp.int32)
        labels = np.array(ids)  # writable copy
        labels[:, ::3] = -100
        loss, _ = eng.train_batch([ids], [jnp.asarray(labels)])
        return float(loss)

    assert abs(one(0) - one(16)) < 1e-4, (one(0), one(16))


def test_chunked_ce_pipe_refuses_loudly():
    import pytest
    from paddle_tpu.nlp.gpt import GPTForCausalLMPipe
    with pytest.raises(NotImplementedError, match="chunked_ce"):
        GPTForCausalLMPipe(GPTConfig(**CFG, chunked_ce=16))


def test_chunked_ce_eval_path_still_returns_logits():
    paddle.seed(5)
    m = GPTForCausalLM(GPTConfig(**CFG, chunked_ce=16))
    m.eval()
    out = m(jnp.ones((1, 8), jnp.int32))
    assert out.shape == [1, 8, 151]  # eval serves logits as usual
