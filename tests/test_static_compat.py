"""paddle.static shim + pdparams checkpoint compatibility."""
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class TestStatic:
    def test_input_spec_and_data(self):
        spec = paddle.static.data("x", [None, 8], "float32")
        assert spec.name == "x"
        assert list(spec.shape) == [None, 8]

    def test_program_gated_with_recipe(self):
        with pytest.raises(NotImplementedError, match="to_static"):
            paddle.static.Program()
        with pytest.raises(NotImplementedError, match="to_static"):
            paddle.static.default_main_program()

    def test_save_load_roundtrip(self, tmp_path):
        from paddle_tpu.jit import InputSpec
        net = nn.Linear(4, 2)
        net.eval()
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        ref = np.asarray(net(x).numpy())
        paddle.static.save(net, str(tmp_path / "m"),
                           input_spec=[InputSpec([2, 4], "float32")])
        loaded = paddle.static.load(str(tmp_path / "m"))
        out = np.asarray(loaded(x).numpy())
        assert np.allclose(out, ref, atol=1e-6)


class TestPdparamsCompat:
    def test_plain_pickle_roundtrip(self, tmp_path):
        # the common real-world layout: pickled {name: ndarray}
        rng = np.random.default_rng(0)
        state = {"fc.weight": rng.standard_normal((4, 2)).astype("float32"),
                 "fc.bias": np.zeros(2, np.float32)}
        p = tmp_path / "model.pdparams"
        with open(p, "wb") as f:
            pickle.dump(state, f, protocol=2)
        loaded = paddle.compat.load_pdparams(str(p))
        assert set(loaded) == set(state)
        assert np.allclose(np.asarray(loaded["fc.weight"].numpy()),
                           state["fc.weight"])

    def test_loads_into_model(self, tmp_path):
        rng = np.random.default_rng(1)
        w = rng.standard_normal((4, 2)).astype("float32")
        b = rng.standard_normal(2).astype("float32")
        p = tmp_path / "m.pdparams"
        with open(p, "wb") as f:
            pickle.dump({"weight": w, "bias": b}, f, protocol=2)
        net = nn.Linear(4, 2)
        net.set_state_dict(paddle.compat.load_pdparams(str(p)))
        x = np.ones((1, 4), np.float32)
        out = np.asarray(net(paddle.to_tensor(x)).numpy())
        assert np.allclose(out, x @ w + b, atol=1e-6)

    def test_paddle_tensor_rebuild_degrades_to_array(self, tmp_path):
        # checkpoints that pickled paddle Tensor wrappers reduce to
        # (rebuild_global, (ndarray, ...)); build such a pickle by faking
        # the paddle module during dump, then load WITHOUT it
        import sys
        import types
        arr = np.arange(6, dtype=np.float32).reshape(2, 3)

        class EagerParamBase:
            def __init__(self, a):
                self.a = a

            def __reduce__(self):
                return (EagerParamBase, (self.a,))

        EagerParamBase.__module__ = "paddle.base.framework"
        EagerParamBase.__qualname__ = "EagerParamBase"
        fake = types.ModuleType("paddle.base.framework")
        fake.EagerParamBase = EagerParamBase
        parents = ["paddle", "paddle.base"]
        added = [m for m in parents if m not in sys.modules]
        for m in added:
            sys.modules[m] = types.ModuleType(m)
        sys.modules["paddle.base.framework"] = fake
        try:
            payload = pickle.dumps({"p": EagerParamBase(arr)}, protocol=2)
        finally:
            for m in added + ["paddle.base.framework"]:
                sys.modules.pop(m, None)
        assert b"paddle.base.framework" in payload
        p = tmp_path / "wrapped.pdparams"
        p.write_bytes(payload)
        loaded = paddle.compat.load_pdparams(str(p), return_numpy=True)
        assert np.allclose(loaded["p"], arr)

    def test_unsupported_paddle_object_fails_loudly(self, tmp_path):
        import sys
        import types

        class Whole:
            def __reduce__(self):
                return (Whole, ())

        Whole.__module__ = "paddle.nn.layer.common"
        Whole.__qualname__ = "Whole"
        fake = types.ModuleType("paddle.nn.layer.common")
        fake.Whole = Whole
        parents = ["paddle", "paddle.nn", "paddle.nn.layer"]
        added = [m for m in parents if m not in sys.modules]
        for m in added:
            sys.modules[m] = types.ModuleType(m)
        sys.modules["paddle.nn.layer.common"] = fake
        try:
            payload = pickle.dumps(Whole(), protocol=2)
        finally:
            for m in added + ["paddle.nn.layer.common"]:
                sys.modules.pop(m, None)
        p = tmp_path / "obj.pdparams"
        p.write_bytes(payload)
        with pytest.raises(Exception, match="unsupported paddle object"):
            paddle.compat.load_pdparams(str(p))

    def test_paddle_load_sniffs_pdparams(self, tmp_path):
        # paddle.load() itself accepts a reference pickle
        state = {"w": np.ones((2, 2), np.float32)}
        p = tmp_path / "ref.pdparams"
        with open(p, "wb") as f:
            pickle.dump(state, f, protocol=2)
        loaded = paddle.load(str(p))
        assert np.allclose(np.asarray(loaded["w"].numpy()), 1.0)

    def test_save_pdparams_readable_by_plain_pickle(self, tmp_path):
        net = nn.Linear(3, 2)
        p = tmp_path / "out.pdparams"
        paddle.compat.save_pdparams(net.state_dict(), str(p))
        with open(p, "rb") as f:
            raw = pickle.load(f)
        assert isinstance(raw["weight"], np.ndarray)
        assert raw["weight"].shape == (3, 2)
