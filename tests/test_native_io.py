"""C++ IO runtime (csrc/libptio.so): queue, pool, gather (SURVEY §2.10)."""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.io import native


pytestmark = pytest.mark.skipif(
    not native.native_available(),
    reason="environmental gate: csrc/libptio.so needs a host g++ to "
           "build (io.native compiles it lazily); without a toolchain "
           "the pure-python DataLoader fallback is the covered path")


def test_queue_fifo_and_backpressure():
    q = native.NativePrefetcher.create(2)
    assert q is not None
    order = []

    def producer():
        for i in range(10):
            assert q.put(("item", i))
        q.put("done")

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item == "done":
            break
        order.append(item[1])
    t.join(timeout=5)
    q.close()
    q.destroy()
    assert order == list(range(10))


def test_queue_close_unblocks_producer():
    q = native.NativePrefetcher.create(1)
    assert q.put(1)  # fills the ring
    results = []

    def producer():
        results.append(q.put(2))  # blocks until close

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.1)
    q.close()
    t.join(timeout=5)
    assert not t.is_alive()
    assert results == [False]
    q.destroy()
    assert q.put(3) is False  # safe after destroy, no crash


def test_queue_close_unblocks_consumer():
    q = native.NativePrefetcher.create(2)
    got = []

    def consumer():
        got.append(q.get())

    t = threading.Thread(target=consumer, daemon=True)
    t.start()
    time.sleep(0.1)
    q.close()
    t.join(timeout=5)
    assert got == [native.NativePrefetcher.CLOSED]
    q.destroy()
    assert q.get() is native.NativePrefetcher.CLOSED  # safe after destroy


def test_buffer_pool_cycle():
    pool = native.BufferPool.create(2, 1024)
    a = pool.acquire()
    b = pool.acquire()
    assert a and b and a[0] != b[0]
    assert a[0] % 64 == 0  # aligned
    pool.release(a[0])
    c = pool.acquire()
    assert c[0] == a[0]  # reused
    pool.release(b[0])
    pool.release(c[0])
    pool.close()
    assert pool.acquire() is None  # closed pool wakes with None
    pool.destroy()


def test_gather_rows_matches_stack():
    rng = np.random.default_rng(0)
    rows = [rng.standard_normal((4, 5)).astype(np.float32)
            for _ in range(8)]
    got = native.gather_rows(rows)
    np.testing.assert_array_equal(got, np.stack(rows))


def test_gather_rows_into_pool_buffer():
    rng = np.random.default_rng(1)
    rows = [rng.integers(0, 100, (16,)).astype(np.int32) for _ in range(4)]
    pool = native.BufferPool.create(1, 4 * 16 * 4)
    addr, _ = pool.acquire()
    got = native.gather_rows(rows, pool_addr=addr)
    np.testing.assert_array_equal(np.array(got), np.stack(rows))
    pool.release(addr)
    pool.destroy()


def test_dataloader_uses_native_prefetch():
    import paddle_tpu as paddle
    from paddle_tpu.io import DataLoader, TensorDataset

    x = np.arange(64, dtype=np.float32).reshape(16, 4)
    y = np.arange(16, dtype=np.int64)
    ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False)
    seen = [np.asarray(bx._value) for bx, _ in dl]
    np.testing.assert_array_equal(np.concatenate(seen), x)


def test_dataloader_early_exit_no_hang():
    import paddle_tpu as paddle
    from paddle_tpu.io import DataLoader, TensorDataset

    x = np.zeros((256, 8), np.float32)
    ds = TensorDataset([paddle.to_tensor(x)])
    dl = DataLoader(ds, batch_size=2, num_workers=2)
    it = iter(dl)
    next(it)
    it.close()  # consumer leaves early; producer must not deadlock


def test_device_prefetch_preserves_order_and_placement():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.io import device_prefetch

    batches = [(np.full((2, 2), i, np.float32), np.array([i])) for i in range(6)]
    out = list(device_prefetch(iter(batches), size=2))
    assert len(out) == 6
    for i, (bx, bi) in enumerate(out):
        assert isinstance(bx, jax.Array)
        np.testing.assert_array_equal(np.asarray(bx), np.full((2, 2), i))
        assert int(bi[0]) == i
