"""MLM masked-position gather (BertConfig.mlm_gather_capacity): loss
and every gradient must EXACTLY match the full [B,S,vocab] head while
the masked count fits the capacity; overflow degrades gracefully."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.hapi.engine import Engine
from paddle_tpu.nlp.bert import (BertConfig, BertForPretraining,
                                 BertPretrainingCriterion)
from paddle_tpu.nlp.ernie import (ErnieConfig, ErnieForPretraining,
                                  ErniePretrainingCriterion)
from paddle_tpu.optimizer import AdamW

TINY = dict(vocab_size=211, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            use_flash_attention=False)


def _labels(rng, b, s, vocab, rate=0.15):
    lab = np.full((b, s), -100, np.int32)
    mask = rng.random((b, s)) < rate
    lab[mask] = rng.integers(0, vocab, mask.sum())
    return jnp.asarray(lab)


def _steps(model_cls, cfg_cls, crit, cap, n_steps=2):
    paddle.seed(23)
    m = model_cls(cfg_cls(**TINY, mlm_gather_capacity=cap))
    m.train()
    eng = Engine(m, loss=crit(),
                 optimizer=AdamW(learning_rate=1e-3,
                                 parameters=m.parameters()))
    rng = np.random.default_rng(5)
    losses = []
    for _ in range(n_steps):
        ids = jnp.asarray(rng.integers(0, 211, (2, 24)), jnp.int32)
        labels = _labels(rng, 2, 24, 211)
        loss, _ = eng.train_batch([ids], [labels])
        losses.append(float(loss))
    return losses, jax.tree_util.tree_leaves(eng._params)


@pytest.mark.parametrize("model_cls,cfg_cls,crit", [
    (BertForPretraining, BertConfig, BertPretrainingCriterion),
    (ErnieForPretraining, ErnieConfig, ErniePretrainingCriterion),
])
def test_gathered_mlm_matches_full_head(model_cls, cfg_cls, crit):
    base_l, base_p = _steps(model_cls, cfg_cls, crit, 0.0)
    g_l, g_p = _steps(model_cls, cfg_cls, crit, 0.4)
    for a, b in zip(base_l, g_l):
        assert abs(a - b) < 1e-4, (base_l, g_l)
    for i, (a, b) in enumerate(zip(base_p, g_p)):
        # the gathered CE sums per-position grads in a different order
        # than the full [B,S,V] reduction; Adam's rsqrt amplifies that
        # float-order noise on near-zero second moments — hence the
        # slightly looser param tolerance (losses above stay at 1e-4)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-3, rtol=2e-3,
                                   err_msg=f"leaf {i}")


def test_overflow_capacity_stays_finite_and_close():
    # capacity below the mask rate: positions drop, loss stays sane
    l, _ = _steps(BertForPretraining, BertConfig,
                  BertPretrainingCriterion, 0.05, n_steps=1)
    assert np.isfinite(l[0])


def test_eval_path_unchanged():
    paddle.seed(1)
    m = BertForPretraining(BertConfig(**TINY, mlm_gather_capacity=0.3))
    m.eval()
    ids = jnp.ones((1, 8), jnp.int32)
    scores, nsp = m(ids)
    assert scores.shape == [1, 8, 211] and nsp.shape == [1, 2]


def test_overflow_count_is_surfaced():
    """Capacity clipping must be detectable (ADVICE r5 #4): the
    criterion exposes last_mlm_overflow = masked positions beyond K on
    the eager path, 0 when everything fits."""
    paddle.seed(2)
    m = BertForPretraining(BertConfig(**TINY, mlm_gather_capacity=0.25))
    m.train()
    crit = BertPretrainingCriterion()
    assert crit.last_mlm_overflow is None  # no gathered call yet
    ids = jnp.asarray(np.random.default_rng(3).integers(0, 211, (2, 24)),
                      jnp.int32)
    # K = max(8, ceil(0.25 * 48)) = 12; mask 20 positions -> overflow 8
    lab = np.full((2, 24), -100, np.int32)
    lab[:, :10] = 5
    from paddle_tpu.tensor import Tensor
    loss = crit(m(Tensor(ids)), Tensor(jnp.asarray(lab)),
                Tensor(jnp.asarray([0, 1], jnp.int32)))
    assert np.isfinite(float(loss._value))
    assert int(crit.last_mlm_overflow._value) == 20 - 12
    # fits-in-capacity batch resets the signal to 0
    lab2 = np.full((2, 24), -100, np.int32)
    lab2[:, :3] = 5
    crit(m(Tensor(ids)), Tensor(jnp.asarray(lab2)),
         Tensor(jnp.asarray([0, 1], jnp.int32)))
    assert int(crit.last_mlm_overflow._value) == 0
