"""Dy2static control-flow story (ref: python/paddle/jit/dy2static/,
convert_operators.py).

The reference AST-transforms data-dependent Python if/while into
cond/while_loop ops. Here @to_static traces with jax.jit; on a tracer-
concretization failure it AST-rewrites simple if/while into
lax.cond/lax.while_loop and retries once; anything un-lowerable raises
a paddle_tpu-voiced ControlFlowError naming the function with the
lax.cond / while_loop / jnp.where migration recipe.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.dy2static import (ControlFlowError, convert_ifelse,
                                      convert_while_loop,
                                      convert_logical_and,
                                      convert_logical_or, UNDEFINED)


def _x(v):
    return paddle.to_tensor(np.asarray(v, np.float32))


# ---------- to_static(Layer) basic path (regression: recursed) --------

class _Plain(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = paddle.nn.Linear(4, 4)

    def forward(self, x):
        return self.fc(x) * 2


def test_to_static_layer_runs_and_matches_eager():
    paddle.seed(0)
    net = _Plain()
    x = _x(np.ones((2, 4)))
    eager = net(x).numpy()
    st = paddle.jit.to_static(net)
    np.testing.assert_allclose(st(x).numpy(), eager, rtol=1e-6)


# ---------- auto-lowered if / while ------------------------------------

class _Branchy(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = paddle.nn.Linear(4, 4)

    def forward(self, x):
        if x.sum() > 0:
            y = self.fc(x)
        else:
            y = x * 0.5
        return y


def test_tensor_if_lowered_to_cond_both_branches():
    paddle.seed(1)
    net = _Branchy()
    st = paddle.jit.to_static(net)
    xp = _x(np.ones((2, 4)))
    xn = _x(-np.ones((2, 4)))
    got_pos = st(xp).numpy()
    got_neg = st(xn).numpy()
    # eager references
    paddle.seed(1)
    ref_net = _Branchy()
    np.testing.assert_allclose(got_pos, ref_net.fc(xp).numpy(), rtol=1e-5)
    np.testing.assert_allclose(got_neg, (xn * 0.5).numpy(), rtol=1e-6)


def test_tensor_while_lowered_to_while_loop():
    @paddle.jit.to_static
    def count_pos(x):
        i = 0
        while (x > 0).sum() > i:
            i = i + 1
        return i

    out = count_pos(_x([1.0, 2.0, -1.0, 3.0]))
    assert int(np.asarray(out.numpy() if hasattr(out, "numpy") else out)) == 3


def test_nested_if_inside_while():
    @paddle.jit.to_static
    def f(x):
        i = 0
        acc = x * 0.0
        while i < 3:
            if x.sum() > 0:
                acc = acc + x
            else:
                acc = acc - x
            i = i + 1
        return acc

    np.testing.assert_allclose(
        f(_x([1.0, 2.0])).numpy(), [3.0, 6.0], rtol=1e-6)
    np.testing.assert_allclose(
        f(_x([-1.0, -2.0])).numpy(), [3.0, 6.0], rtol=1e-6)


def test_boolop_in_condition_converted():
    @paddle.jit.to_static
    def f(x):
        if (x.sum() > 0) and (x.max() < 10):
            y = x * 2.0
        else:
            y = x * 3.0
        return y

    np.testing.assert_allclose(f(_x([1.0, 2.0])).numpy(), [2.0, 4.0])
    np.testing.assert_allclose(f(_x([1.0, 20.0])).numpy(), [3.0, 60.0])


# ---------- un-lowerable patterns speak paddle_tpu ---------------------

class _EarlyReturn(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = paddle.nn.Linear(4, 4)

    def forward(self, x):
        if x.sum() > 0:
            return self.fc(x)
        return x


def test_return_in_tensor_branch_raises_actionable_error():
    paddle.seed(2)
    st = paddle.jit.to_static(_EarlyReturn())
    with pytest.raises(ControlFlowError) as ei:
        st(_x(np.ones((2, 4))))
    msg = str(ei.value)
    assert "forward" in msg          # names the function
    assert "lax.cond" in msg         # migration recipe
    assert "while_loop" in msg
    assert "where" in msg


def test_one_sided_assignment_raises_actionable_error():
    @paddle.jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * 2.0        # y undefined in else-branch
        return y

    with pytest.raises(ControlFlowError):
        f(_x([1.0, 2.0]))


def test_tensor_range_raises_actionable_error():
    @paddle.jit.to_static
    def f(x):
        acc = x.sum() * 0
        for i in range(int(x.sum())):
            acc = acc + i
        return acc

    with pytest.raises(ControlFlowError) as ei:
        f(_x([3.0]))
    assert "fori_loop" in str(ei.value)


def test_raise_in_tensor_branch_not_lowered():
    """A data-dependent `raise` must NOT become a lax.cond branch: both
    branches trace unconditionally, so the raise would fire for every
    input. It must surface as ControlFlowError, not a spurious
    ValueError."""
    @paddle.jit.to_static
    def f(x):
        if x.sum() < 0:
            raise ValueError("negative input")
        y = x * 2.0
        return y

    with pytest.raises(ControlFlowError):
        f(_x([1.0, 2.0]))            # positive input — raise must not fire


class _Base(paddle.nn.Layer):
    def forward(self, x):
        return x + 1.0


class _Sub(_Base):
    def forward(self, x):
        h = super().forward(x)
        if h.sum() > 0:
            y = h * 2.0
        else:
            y = h * 3.0
        return y


def test_zero_arg_super_rewritten():
    st = paddle.jit.to_static(_Sub())
    np.testing.assert_allclose(st(_x([1.0, 2.0])).numpy(), [4.0, 6.0])
    np.testing.assert_allclose(st(_x([-4.0, -4.0])).numpy(), [-9.0, -9.0])


def _plus_one(fn):
    import functools

    @functools.wraps(fn)
    def wrap(*a, **k):
        return fn(*a, **k) + 1.0
    return wrap


def test_stacked_decorator_preserved_through_rewrite():
    @paddle.jit.to_static
    @_plus_one
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x * 3.0
        return y

    np.testing.assert_allclose(f(_x([1.0])).numpy(), [3.0])   # 2x + 1
    np.testing.assert_allclose(f(_x([-1.0])).numpy(), [-2.0])  # 3x + 1


def test_enable_to_static_false_uses_pristine_original():
    @paddle.jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x * 3.0
        return y

    f(_x([1.0]))                     # triggers the dy2static rewrite
    paddle.jit.enable_to_static(False)
    try:
        out = f(_x([1.0]))           # eager: original source, concrete if
        np.testing.assert_allclose(np.asarray(out.numpy() if
                                   hasattr(out, "numpy") else out), [2.0])
    finally:
        paddle.jit.enable_to_static(True)


# ---------- convert_* public operators ---------------------------------

def test_convert_ifelse_python_pred_short_circuits():
    calls = []

    def t(v):
        calls.append("t")
        return (1,)

    def f(v):
        calls.append("f")
        return (2,)

    assert convert_ifelse(True, t, f, (0,)) == (1,)
    assert calls == ["t"]            # false branch never ran


def test_convert_ifelse_tracer_pred_uses_cond():
    import jax

    def run(x):
        return convert_ifelse(x.sum() > 0,
                              lambda c: (c[0] + 1.0,),
                              lambda c: (c[0] - 1.0,), (x.sum(),))[0]

    out = jax.jit(run)(jnp.asarray([2.0, 3.0]))
    assert float(out) == 6.0


def test_convert_while_loop_python_cond():
    out = convert_while_loop(lambda c: c[0] < 5,
                             lambda c: (c[0] + 2,), (0,))
    assert out == (6,)


def test_convert_logical_ops_short_circuit_python():
    seen = []

    def rhs():
        seen.append(1)
        return True

    assert convert_logical_and(lambda: False, rhs) is False
    assert seen == []                # short-circuit kept
    assert convert_logical_or(lambda: True, rhs) is True
    assert seen == []


def test_enable_to_static_false_runs_original_eagerly():
    @paddle.jit.to_static
    def f(x):
        if x.sum() > 0:              # fine eagerly: concrete values
            return x * 2.0
        return x

    paddle.jit.enable_to_static(False)
    try:
        np.testing.assert_allclose(f(_x([1.0])).numpy(), [2.0])
    finally:
        paddle.jit.enable_to_static(True)


def test_undefined_sentinel_is_singleton_static_node():
    import jax
    leaves, treedef = jax.tree_util.tree_flatten((UNDEFINED, 1.0))
    assert leaves == [1.0]           # UNDEFINED is structure, not a leaf
    back = jax.tree_util.tree_unflatten(treedef, leaves)
    assert back[0] is UNDEFINED


def test_elif_chain_lowered():
    """elif nests an If inside orelse; the transformer must lower the
    whole chain."""
    @paddle.jit.to_static
    def f(x):
        s = x.sum()
        if s > 10.0:
            y = x * 1.0
        elif s > 0.0:
            y = x * 2.0
        else:
            y = x * 3.0
        return y

    np.testing.assert_allclose(f(_x([20.0])).numpy(), [20.0])
    np.testing.assert_allclose(f(_x([1.0])).numpy(), [2.0])
    np.testing.assert_allclose(f(_x([-1.0])).numpy(), [-3.0])


def test_static_python_branch_untouched():
    """A Python-valued condition must keep eager short-circuit semantics
    even after the function was AST-transformed for a tensor branch."""
    calls = []

    def probe():
        calls.append(1)
        return True

    @paddle.jit.to_static
    def f(x, flag):
        if flag and probe():         # python condition: lazy evaluation
            y = x * 2.0
        else:
            y = x * 3.0
        if x.sum() > 0:              # tensor condition: forces transform
            z = y + 1.0
        else:
            z = y - 1.0
        return z

    np.testing.assert_allclose(f(_x([1.0]), True).numpy(), [3.0])
    np.testing.assert_allclose(f(_x([1.0]), False).numpy(), [4.0])
    assert len(calls) >= 1           # probe ran for flag=True traces


def test_static_for_range_unrolls():
    """Static-bound for loops trace by unrolling — no transform, no
    error."""
    @paddle.jit.to_static
    def f(x):
        for _ in range(3):
            x = x * 2.0
        return x

    np.testing.assert_allclose(f(_x([1.0])).numpy(), [8.0])


def test_while_with_augassign():
    @paddle.jit.to_static
    def f(x):
        total = x.sum() * 0.0
        i = 0
        while i < 4:
            total += x.sum()
            i += 1
        return total

    np.testing.assert_allclose(
        np.asarray(f(_x([1.5, 0.5])).numpy()), 8.0, rtol=1e-6)


# ---------- tracer accounting (ISSUE 13: to_static through the tracer)

def test_to_static_compiles_land_in_tracer_accounting():
    """A to_static trace is a compile the zero-recompile report must
    see: per-wrapper train/eval sites, one trace each, and a repeat
    call (cached program) must not bump anything."""
    from paddle_tpu.observability.trace import get_tracer
    paddle.seed(0)
    net = _Plain()
    st = paddle.jit.to_static(net)
    x = _x(np.ones((2, 4)))
    st(x); st(x)
    net.eval()
    st(x)
    tracer = get_tracer()
    prefix = st.forward._site   # to_static(Layer) returns the layer;
    #                             the StaticFunction is its forward
    sites = {s: n for s, n in tracer.counts().items()
             if s.startswith(prefix)}
    assert sorted(s.rsplit("_", 1)[1] for s in sites) \
        == ["eval", "train"], sites
    assert all(n == 1 for n in sites.values()), sites
    assert tracer.report()["unexpected_retraces"] == 0


def test_to_static_wrapper_gc_releases_tracer_sites():
    """Dynamically-minted sites die with their wrapper (a
    wrapper-churning process must not grow the tracer without
    bound) — but a site that saw an unexpected retrace is KEPT, so
    churn can't launder the signal out of the report."""
    import gc
    from paddle_tpu.observability.trace import get_tracer
    paddle.seed(0)
    net = _Plain()
    st = paddle.jit.to_static(net)
    st(_x(np.ones((2, 4))))
    site_prefix = st.forward._site
    tracer = get_tracer()
    assert any(s.startswith(site_prefix) for s in tracer.counts())
    del st, net   # to_static(Layer) returned `net` itself
    gc.collect()
    assert not any(s.startswith(site_prefix)
                   for s in tracer.counts())
    # forget() refuses when the site carries a retrace signal
    tracer._counts["phantom_site"] = 2
    tracer._unexpected["phantom_site"] = 1
    try:
        assert tracer.forget("phantom_site") is False
        assert "phantom_site" in tracer.counts()
    finally:
        tracer._unexpected.pop("phantom_site", None)
        tracer.forget("phantom_site")
