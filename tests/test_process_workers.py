"""Spawn-based DataLoader worker processes + shared-memory transport
(ref: python/paddle/io/dataloader/worker.py _worker_loop). Kept tiny:
spawn costs seconds on this 1-core box, so ONE pool exercises order,
values, worker_init_fn, get_worker_info, and error propagation."""
import numpy as np
import pytest

from paddle_tpu.io import DataLoader, Dataset

pytestmark = pytest.mark.filterwarnings("ignore::UserWarning")


class SquaresDataset(Dataset):
    """Module-level (picklable) dataset; item i -> [i, i*i] float32."""

    def __init__(self, n=64):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        from paddle_tpu.io.dataloader import get_worker_info
        info = get_worker_info()
        assert info is not None and 0 <= info.id < info.num_workers
        return np.asarray([i, i * i], dtype=np.float32)


class BoomDataset(SquaresDataset):
    def __getitem__(self, i):
        if i == 13:
            raise RuntimeError("boom at 13")
        return super().__getitem__(i)


def _init(worker_id):
    import os
    os.environ["PT_TEST_WORKER_INIT"] = str(worker_id)


def test_process_pool_order_values_and_info():
    dl = DataLoader(SquaresDataset(64), batch_size=8, shuffle=False,
                    num_workers=2, use_process_workers=True,
                    worker_init_fn=_init)
    got = [np.asarray(b._value if hasattr(b, "_value") else b)
           for b in dl]
    assert len(got) == 8
    flat = np.concatenate(got)[:, 0]
    # in-order delivery despite 2 out-of-order workers
    np.testing.assert_array_equal(flat, np.arange(64, dtype=np.float32))
    np.testing.assert_array_equal(np.concatenate(got)[:, 1],
                                  (np.arange(64) ** 2).astype(np.float32))


def test_worker_error_propagates():
    dl = DataLoader(BoomDataset(32), batch_size=8, shuffle=False,
                    num_workers=2, use_process_workers=True)
    with pytest.raises(RuntimeError, match="boom at 13"):
        list(dl)


def test_persistent_pool_reused_across_epochs():
    dl = DataLoader(SquaresDataset(16), batch_size=8, shuffle=False,
                    num_workers=2, use_process_workers=True,
                    persistent_workers=True)
    list(dl)
    pool1 = dl._pool
    assert pool1 is not None and not pool1._closed
    got = [np.asarray(b._value if hasattr(b, "_value") else b)
           for b in dl]
    assert dl._pool is pool1  # same spawn pool, no per-epoch respawn
    np.testing.assert_array_equal(np.concatenate(got)[:, 0],
                                  np.arange(16, dtype=np.float32))
    pool1.shutdown()


def test_unpicklable_raises_actionable():
    dl = DataLoader(SquaresDataset(8), batch_size=4, num_workers=2,
                    use_process_workers=True,
                    collate_fn=lambda b: np.stack(b))
    with pytest.raises(ValueError, match="does not pickle"):
        iter(dl).__next__()
