"""Observability subsystem (ISSUE 4): metrics registry, recompile
tracer, structured run telemetry, and their wiring into hapi / serving
/ dataloader / profiler.

Pins the contracts docs/observability.md documents:
- histogram bucket math (log-spaced 1-2-5 ladder, count-weighted
  observe, bucket-interpolated quantiles) and snapshot MERGE;
- Prometheus-text and JSON export golden strings;
- RecompileTracer: an intentional shape change is a trace with a fresh
  signature (expected), re-tracing a seen signature is UNEXPECTED, and
  a zero-recompile serve wave records nothing after warmup;
- TelemetryCallback: skip/rollback counts consistent with TrainGuard
  under an injected NaN storm (resilience.faults seams);
- TelemetryLogger JSONL rotation + torn-line-tolerant summarize();
- ServingEngine health()/reset_counters() uniform reset through the
  registry (the retry/watchdog-survives-reset divergence, fixed).
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.observability.metrics import (Counter, Gauge, Histogram,
                                              MetricsRegistry,
                                              default_time_buckets,
                                              get_registry)
from paddle_tpu.observability.telemetry import (TelemetryCallback,
                                                TelemetryLogger)
from paddle_tpu.observability.trace import RecompileTracer, report_all
from paddle_tpu.resilience import TrainGuard, faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# -- histogram math -------------------------------------------------------

class TestHistogram:
    def test_default_buckets_are_125_ladder(self):
        b = default_time_buckets(-2, 0)
        assert b == (0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0)
        assert list(b) == sorted(b)

    def test_observe_bucketing_and_overflow(self):
        h = Histogram("h", buckets=(1.0, 2.0, 5.0))
        for v in (0.5, 1.0, 1.5, 4.0, 100.0):
            h.observe(v)
        # counts: (..1], (1..2], (2..5], overflow
        assert h.counts == [2, 1, 1, 1]
        assert h.count == 5
        assert h.min == 0.5 and h.max == 100.0
        assert h.sum == pytest.approx(107.0)

    def test_count_weighted_observe(self):
        h = Histogram("h", buckets=(1.0, 10.0))
        h.observe(0.25, count=8)   # a K-token dispatch in O(1)
        assert h.count == 8
        assert h.counts == [8, 0, 0]
        assert h.sum == pytest.approx(2.0)
        assert h.mean() == pytest.approx(0.25)

    def test_quantiles_interpolate_within_min_max(self):
        h = Histogram("h", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.6, 3.0):
            h.observe(v)
        assert h.quantile(0.0) >= h.min
        assert h.quantile(1.0) == pytest.approx(h.max)
        p50 = h.quantile(0.5)
        assert 1.0 <= p50 <= 2.0, "median sits in the (1,2] bucket"
        assert Histogram("e").quantile(0.5) is None

    def test_merge_adds_buckets_and_tracks_extrema(self):
        a = Histogram("h", buckets=(1.0, 2.0))
        b = Histogram("h", buckets=(1.0, 2.0))
        a.observe(0.5)
        b.observe(1.5)
        b.observe(9.0)
        a.merge(b.snapshot())
        assert a.count == 3
        assert a.counts == [1, 1, 1]
        assert a.min == 0.5 and a.max == 9.0
        assert a.sum == pytest.approx(11.0)

    def test_merge_rejects_mismatched_bounds(self):
        a = Histogram("h", buckets=(1.0, 2.0))
        b = Histogram("h", buckets=(1.0, 2.0, 4.0))
        with pytest.raises(ValueError, match="mismatched bucket"):
            a.merge(b.snapshot())


# -- registry: series identity, merge, reset ------------------------------

class TestRegistry:
    def test_series_identity_and_type_guard(self):
        reg = MetricsRegistry()
        c1 = reg.counter("req", labels={"status": "ok"})
        c2 = reg.counter("req", labels={"status": "ok"})
        c3 = reg.counter("req", labels={"status": "bad"})
        assert c1 is c2 and c1 is not c3
        with pytest.raises(TypeError, match="already registered"):
            reg.gauge("req", labels={"status": "ok"})

    def test_merge_counters_add_gauges_last_win(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("n").inc(2)
        b.counter("n").inc(3)
        a.gauge("g").set(1.0)
        b.gauge("g").set(7.0)
        b.histogram("h", buckets=(1.0,)).observe(0.5)
        a.merge(b.snapshot())
        assert a.counter("n").value == 5
        assert a.gauge("g").value == 7.0
        assert a.get("h").count == 1

    def test_reset_zeroes_in_place(self):
        reg = MetricsRegistry()
        c = reg.counter("n")
        h = reg.histogram("h", buckets=(1.0,))
        c.inc(4)
        h.observe(0.5)
        reg.reset()
        assert c.value == 0, "the held handle must stay live"
        assert h.count == 0 and h.min is None

    def test_concurrent_scrape_during_registration(self):
        # a scrape thread iterating the registry while the main thread
        # lazily registers new series must not crash with "dictionary
        # changed size during iteration"
        import threading
        reg = MetricsRegistry()
        stop = threading.Event()
        errs = []

        def scrape():
            while not stop.is_set():
                try:
                    reg.to_prometheus()
                    reg.snapshot()
                    reg.names()
                except Exception as e:  # pragma: no cover
                    errs.append(e)
                    return

        t = threading.Thread(target=scrape)
        t.start()
        try:
            for i in range(300):
                reg.counter("c", labels={"i": str(i)}).inc()
                reg.histogram("h", labels={"i": str(i)}).observe(0.1)
        finally:
            stop.set()
            t.join()
        assert not errs, errs

    def test_dump_is_parseable_with_extra(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("n").inc()
        p = reg.dump(str(tmp_path / "metrics.json"),
                     extra={"recompile_report": {"unexpected": 0}})
        doc = json.loads(open(p).read())
        assert doc["metrics"]["n"]["value"] == 1
        assert doc["recompile_report"] == {"unexpected": 0}


# -- export golden strings ------------------------------------------------

class TestExports:
    def _golden_registry(self):
        reg = MetricsRegistry()
        reg.counter("requests_total", help="served requests",
                    labels={"status": "ok"}).inc(3)
        reg.gauge("free_pages").set(5)
        h = reg.histogram("latency_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5, count=2)
        return reg

    def test_prometheus_golden(self):
        text = self._golden_registry().to_prometheus()
        assert text == (
            "# TYPE free_pages gauge\n"
            "free_pages 5\n"
            "# TYPE latency_seconds histogram\n"
            'latency_seconds_bucket{le="0.1"} 1\n'
            'latency_seconds_bucket{le="1.0"} 3\n'
            'latency_seconds_bucket{le="+Inf"} 3\n'
            "latency_seconds_sum 1.05\n"
            "latency_seconds_count 3\n"
            "# HELP requests_total served requests\n"
            "# TYPE requests_total counter\n"
            'requests_total{status="ok"} 3\n')

    def test_json_golden_roundtrip(self):
        doc = json.loads(self._golden_registry().to_json())
        m = doc["metrics"]
        assert m['requests_total{status="ok"}'] == {
            "name": "requests_total", "labels": {"status": "ok"},
            "type": "counter", "value": 3}
        assert m["latency_seconds"]["counts"] == [1, 2, 0]
        assert m["latency_seconds"]["sum"] == pytest.approx(1.05)
        fresh = MetricsRegistry()
        fresh.merge(doc)   # a dumped snapshot is a mergeable snapshot
        assert fresh.get("free_pages").value == 5


# -- recompile tracer -----------------------------------------------------

class TestRecompileTracer:
    def test_trace_once_then_silent(self):
        import jax.numpy as jnp
        reg = MetricsRegistry()
        tr = RecompileTracer(name="t", registry=reg)
        f = tr.jit("add", lambda x: x + 1)
        for _ in range(3):
            f(jnp.zeros((4,)))
        assert tr.counts() == {"add": 1}
        assert tr.unexpected_retraces() == 0
        [e] = tr.events()
        assert e["site"] == "add" and not e["unexpected"]
        assert "[4]" in e["signature"] and "float" in e["signature"]
        assert reg.counter("recompile_traces_total",
                           labels={"tracer": "t",
                                   "site": "add"}).value == 1

    def test_shape_change_is_expected_new_signature(self):
        import jax.numpy as jnp
        tr = RecompileTracer(name="t")
        f = tr.jit("add", lambda x: x + 1)
        f(jnp.zeros((4,)))
        f(jnp.zeros((8,)))   # intentional retrace: NEW signature
        assert tr.counts()["add"] == 2
        assert tr.unexpected_retraces() == 0
        rep = tr.report()
        assert rep["sites"]["add"] == {"traces": 2, "signatures": 2,
                                       "unexpected_retraces": 0}

    def test_seen_signature_retrace_is_unexpected(self):
        import jax.numpy as jnp
        tr = RecompileTracer(name="t", registry=MetricsRegistry())
        f = tr.jit("add", lambda x: x + 1)
        f(jnp.zeros((4,)))
        # drop THIS function's compiled program (the cliff), without
        # jax.clear_caches() nuking other tests' warm programs
        f.jitted.clear_cache()
        f(jnp.zeros((4,)))
        assert tr.counts()["add"] == 2
        assert tr.unexpected_retraces() == 1
        assert [e["unexpected"] for e in tr.events()] == [False, True]

    def test_report_all_merges_live_tracers(self):
        import jax.numpy as jnp
        tr = RecompileTracer(name="zz-report-all-test")
        tr.jit("f", lambda x: x * 2)(jnp.ones(()))
        rep = report_all()
        names = [t["tracer"] for t in rep["tracers"]]
        assert "zz-report-all-test" in names

    def test_serve_wave_traces_warmup_only(self, tmp_path):
        """The acceptance shape: a zero-recompile serve wave records
        warmup traces and NOTHING after — and the instrumentation
        itself (histograms, health snapshots) induces no retrace."""
        from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
        from paddle_tpu.nlp.serving import ServingEngine
        paddle.seed(0)
        model = GPTForCausalLM(_resolve_config("gpt-tiny"))
        reg = MetricsRegistry()
        eng = ServingEngine(model, max_slots=2, page_size=16,
                            max_seq_len=48, steps_per_dispatch=2,
                            registry=reg)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 256, (6,)) for _ in range(4)]
        eng.generate(prompts, max_new_tokens=4)      # warmup wave
        events_after_warmup = len(eng.tracer.events())
        eng.reset_counters()
        eng.generate(prompts, max_new_tokens=4)      # steady wave
        eng.health()
        assert len(eng.tracer.events()) == events_after_warmup, \
            "steady-state wave must record zero trace events"
        assert eng.tracer.unexpected_retraces() == 0
        assert reg.get("serve_ttft_seconds").count == 4
        assert reg.get("serve_decode_token_seconds").count > 0


# -- telemetry logger: JSONL + rotation -----------------------------------

class TestTelemetryLogger:
    def test_emit_and_summarize(self, tmp_path):
        lg = TelemetryLogger(str(tmp_path))
        lg.emit("train_step", step=1, loss=2.0)
        lg.emit("train_step", step=2, loss=1.0)
        lg.emit("serve_request", ttft_ms=5.0)
        s = lg.summarize()
        assert s["records"] == 3
        st = s["by_kind"]["train_step"]["fields"]["loss"]
        assert st == {"min": 1.0, "max": 2.0, "last": 1.0, "mean": 1.5}
        lg.close()

    def test_rotation_keeps_bounded_files(self, tmp_path):
        lg = TelemetryLogger(str(tmp_path), rotate_bytes=200,
                             max_rotated=2)
        for i in range(50):
            lg.emit("r", i=i, pad="x" * 40)
        assert lg.rotations >= 3
        lg.flush()
        files = lg.files()
        assert [os.path.basename(f) for f in files] == [
            "telemetry.jsonl.2", "telemetry.jsonl.1",
            "telemetry.jsonl"]
        recs = list(lg.iter_records())
        assert recs, "retained files must still parse"
        # newest record survives; the oldest rotated out
        assert recs[-1]["i"] == 49
        assert recs[0]["i"] > 0
        lg.close()

    def test_nan_loss_emits_valid_json(self, tmp_path):
        """A NaN loss (the storm the guard records) must land as RFC
        JSON (null), never a bare NaN token jq/JS consumers reject."""
        lg = TelemetryLogger(str(tmp_path))
        lg.emit("train_step", loss=float("nan"), step_time_s=0.1,
                nested={"g": float("inf")})
        lg.close()
        raw = open(lg.path).read()
        assert "NaN" not in raw and "Infinity" not in raw
        rec = json.loads(raw.splitlines()[0])
        assert rec["loss"] is None and rec["nested"]["g"] is None
        assert rec["step_time_s"] == 0.1

    def test_nan_gauge_dumps_valid_json(self, tmp_path):
        reg = MetricsRegistry()
        reg.gauge("train_loss").set(float("nan"))
        reg.counter("ok_total").inc(2)
        path = reg.dump(str(tmp_path / "metrics.json"))
        raw = open(path).read()
        assert "NaN" not in raw
        doc = json.loads(raw)
        assert doc["metrics"]["train_loss"]["value"] is None
        assert reg.to_json()  # parseable too
        assert "NaN" not in reg.to_json()

    def test_torn_line_does_not_kill_rollup(self, tmp_path):
        lg = TelemetryLogger(str(tmp_path))
        lg.emit("r", i=1)
        lg.flush()
        with open(lg.path, "a") as f:
            f.write('{"kind": "r", "i": 2')   # torn crash write
        assert lg.summarize()["records"] == 1
        lg.close()


# -- TelemetryCallback under a NaN storm ----------------------------------

class TestTelemetryCallback:
    def _fit(self, tmp_path, registry, storm=None):
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        guard = TrainGuard(snapshot_every=1, rollback_after=3)
        model.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss(), guard=guard)
        rng = np.random.default_rng(0)
        X = rng.standard_normal((32, 8)).astype("float32")
        Y = rng.integers(0, 4, (32,)).astype("int64")
        cb = TelemetryCallback(run_dir=str(tmp_path), registry=registry)
        if storm:
            faults.inject("nan_grads", step=storm[0], count=storm[1])
        model.fit(paddle.io.TensorDataset([X, Y]), epochs=1,
                  batch_size=4, verbose=0, shuffle=False,
                  callbacks=[cb])
        return guard, cb

    def test_storm_counts_match_guard(self, tmp_path):
        reg = MetricsRegistry()
        guard, cb = self._fit(tmp_path, reg, storm=(3, 3))
        assert guard.skipped_steps == 3
        assert guard.rollbacks == 1
        assert reg.counter("train_skipped_steps_total").value == 3
        assert reg.counter("train_rollbacks_total").value == 1
        assert reg.counter("train_steps_total").value == 8
        assert reg.get("train_step_seconds").count == 8
        assert reg.gauge("train_loss").value > 0
        assert reg.gauge("train_samples_per_s").value > 0
        assert reg.gauge("train_grad_norm").value >= 0
        # JSONL records carry the same story, step by step
        recs = [r for r in cb.logger.iter_records()
                if r["kind"] == "train_step"]
        assert len(recs) == 8
        assert [r["outcome"] for r in recs] == (
            ["ok", "ok", "skipped", "skipped", "rolled_back",
             "ok", "ok", "ok"])
        assert recs[-1]["skipped"] == 3 and recs[-1]["rollbacks"] == 1
        end = [r for r in cb.logger.iter_records()
               if r["kind"] == "train_end"]
        assert end and end[0]["skipped_steps"] == 3

    def test_clean_run_exports_zero_counters(self, tmp_path):
        """A clean run exports the guard counters AT ZERO — absent
        metrics are indistinguishable from broken wiring."""
        reg = MetricsRegistry()
        guard, cb = self._fit(tmp_path, reg)
        assert reg.counter("train_skipped_steps_total").value == 0
        assert reg.counter("train_rollbacks_total").value == 0
        assert cb.metrics_path and os.path.exists(cb.metrics_path)
        doc = json.load(open(cb.metrics_path))
        assert "recompile_report" in doc
        # scope to THIS fit's engine: report_all() spans every tracer
        # the process ever made, including other tests' deliberate
        # retraces (tracers register strongly — see trace.py)
        assert cb.model._engine.tracer.unexpected_retraces() == 0

    def test_second_fit_does_not_recount_history(self, tmp_path):
        """Guard/scaler totals are lifetime-absolute on the guard; a
        second fit() on the same model must baseline them at
        train_begin and diff only ITS OWN skips into the registry."""
        reg = MetricsRegistry()
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        guard = TrainGuard(snapshot_every=1, rollback_after=3)
        model.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss(), guard=guard)
        rng = np.random.default_rng(0)
        X = rng.standard_normal((32, 8)).astype("float32")
        Y = rng.integers(0, 4, (32,)).astype("int64")
        ds = paddle.io.TensorDataset([X, Y])
        faults.inject("nan_grads", step=3, count=3)
        model.fit(ds, epochs=1, batch_size=4, verbose=0, shuffle=False,
                  callbacks=[TelemetryCallback(run_dir=str(tmp_path),
                                               registry=reg)])
        assert guard.skipped_steps == 3
        assert reg.counter("train_skipped_steps_total").value == 3
        # clean second fit: fresh callback, same guard + registry —
        # the counters must NOT double to 6/2
        model.fit(ds, epochs=1, batch_size=4, verbose=0, shuffle=False,
                  callbacks=[TelemetryCallback(run_dir=str(tmp_path),
                                               registry=reg)])
        assert guard.skipped_steps == 3
        assert reg.counter("train_skipped_steps_total").value == 3
        assert reg.counter("train_rollbacks_total").value == 1
        assert reg.counter("train_steps_total").value == 16

    def test_grad_norm_is_opt_in(self, tmp_path):
        """A bare Engine (no TelemetryCallback) must not pay the
        in-step grad-norm reduction: last_grad_norm stays None and the
        compiled step matches pre-telemetry baselines. With the
        callback attached, the same step exports a real norm."""
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        eng = model._engine
        assert not eng.collect_grad_norm
        x = np.zeros((4, 8), dtype="float32")
        y = np.zeros((4,), dtype="int64")
        model.train_batch([x], [y])
        assert eng.last_grad_norm is None

        reg = MetricsRegistry()
        guard, cb = self._fit(tmp_path, reg)
        recs = [r for r in cb.logger.iter_records()
                if r["kind"] == "train_step"]
        assert all(r.get("grad_norm") is not None for r in recs)
        assert cb.model._engine.collect_grad_norm

    def test_grad_norm_cleared_on_accum_and_multi_paths(self):
        """train_batch_accum / train_batch_multi compute no global
        grad norm; they must CLEAR last_grad_norm so a later telemetry
        read never reports a stale fused-step value as current."""
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        eng = model._engine
        eng.enable_grad_norm()
        x = np.zeros((4, 8), dtype="float32")
        y = np.zeros((4,), dtype="int64")
        model.train_batch([x], [y])
        assert eng.last_grad_norm is not None
        eng.train_batch_accum([x], [y], apply_update=True)
        assert eng.last_grad_norm is None

        model.train_batch([x], [y])
        assert eng.last_grad_norm is not None
        xs = np.stack([x, x])
        ys = np.stack([y, y])
        eng.train_batch_multi([xs], [ys])
        assert eng.last_grad_norm is None

    def test_dataloader_batch_wait_lands_in_global_registry(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        reg = get_registry()
        train = {"role": "train"}
        before = reg.get("dataloader_batches_total", labels=train)
        before = before.value if before else 0
        X = np.zeros((8, 3), "float32")
        n = sum(1 for _ in DataLoader(TensorDataset([X]), batch_size=2))
        assert n == 4
        assert reg.counter("dataloader_batches_total",
                           labels=train).value == before + 4
        assert reg.get("dataloader_batch_wait_seconds",
                       labels=train).count >= 4

    def test_dataloader_role_label_separates_eval_from_train(self):
        # eval/predict loaders must not pollute the train batch-wait
        # series (the input-bound-run diagnostic)
        from paddle_tpu.io import DataLoader, TensorDataset
        reg = get_registry()
        train = reg.counter("dataloader_batches_total",
                            labels={"role": "train"}).value
        X = np.zeros((6, 3), "float32")
        loader = DataLoader(TensorDataset([X]), batch_size=2)
        loader._obs_role = "eval"
        assert sum(1 for _ in loader) == 3
        assert reg.counter("dataloader_batches_total",
                           labels={"role": "eval"}).value >= 3
        assert reg.counter("dataloader_batches_total",
                           labels={"role": "train"}).value == train


# -- serving reset/health uniformity (the ISSUE 4 divergence fix) ---------

class TestServeResetUniformity:
    @pytest.fixture(scope="class")
    def engine(self):
        from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
        from paddle_tpu.nlp.serving import ServingEngine
        paddle.seed(0)
        model = GPTForCausalLM(_resolve_config("gpt-tiny"))
        eng = ServingEngine(model, max_slots=2, page_size=16,
                            max_seq_len=48, steps_per_dispatch=2,
                            dispatch_retries=2,
                            registry=MetricsRegistry())
        yield eng
        eng.close()

    def test_reset_clears_retry_and_status_fields(self, engine):
        rng = np.random.default_rng(0)
        faults.inject("dispatch_error", count=1)
        engine.generate([rng.integers(0, 256, (6,))], max_new_tokens=4)
        h = engine.health()
        assert h["dispatch_retries"] == 1
        assert h["status_counts"]["ok"] == 1
        assert h["deadline_misses"] == 0
        engine.reset_counters()
        h2 = engine.health()
        assert h2["dispatch_retries"] == 0, \
            "retry count must not survive reset_counters()"
        assert h2["status_counts"]["ok"] == 0
        assert h2["decode_tokens"] == 0
        # live state (pages, queue) is NOT a counter: still truthful
        assert h2["free_pages"] == engine.free_page_count

    def test_counters_resume_after_reset(self, engine):
        rng = np.random.default_rng(1)
        engine.generate([rng.integers(0, 256, (6,))], max_new_tokens=4)
        h = engine.health()
        assert h["status_counts"]["ok"] == 1
        assert h["page_occupancy"] == 0.0, "drained pool reads empty"


class TestServeRegistryIsolation:
    def test_default_registries_are_per_engine(self):
        """Two engines with the default registry must not alias each
        other's serve_* series: counts stay per-engine and one
        engine's reset cannot zero a sibling's window."""
        from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
        from paddle_tpu.nlp.serving import ServingEngine
        from paddle_tpu.observability.metrics import get_registry
        paddle.seed(0)
        model = GPTForCausalLM(_resolve_config("gpt-tiny"))
        a = ServingEngine(model, max_slots=1, page_size=16,
                          max_seq_len=48, steps_per_dispatch=2)
        b = ServingEngine(model, max_slots=1, page_size=16,
                          max_seq_len=48, steps_per_dispatch=2)
        try:
            assert a.registry is not b.registry
            assert a.registry is not get_registry()
            rng = np.random.default_rng(0)
            a.generate([rng.integers(0, 256, (6,))], max_new_tokens=4)
            assert a.health()["status_counts"]["ok"] == 1
            assert b.health()["status_counts"]["ok"] == 0
            b.reset_counters()
            assert a.health()["status_counts"]["ok"] == 1, \
                "a sibling's reset_counters() must not zero this engine"
        finally:
            a.close()
            b.close()

    def test_closed_tracer_report_retained(self):
        """close() deregisters the tracer (no unbounded growth across
        engine reloads) but its site aggregates stay in report_all."""
        from paddle_tpu.observability.trace import (RecompileTracer,
                                                    all_tracers,
                                                    report_all)
        tr = RecompileTracer(name="retired", registry=MetricsRegistry())
        f = tr.jit("square", lambda x: x * x)
        f(np.arange(4.0, dtype=np.float32))
        tr.close()
        assert tr not in all_tracers()
        tr.close()  # idempotent
        mine = [t for t in report_all()["tracers"]
                if t["tracer"] == "retired"]
        assert len(mine) == 1 and mine[0]["closed"]
        assert mine[0]["sites"]["square"]["traces"] == 1
        assert mine[0]["events"] == []

    def test_closed_aggregate_never_evicts(self):
        """An unexpected retrace recorded by an early engine must
        survive ANY number of later tracer retirements — closed
        tracers fold into a cumulative per-name rollup, not a bounded
        list that silently evicts the one fact the report exists to
        keep."""
        import jax.numpy as jnp
        from paddle_tpu.observability.trace import (RecompileTracer,
                                                    report_all)
        early = RecompileTracer(name="agg-victim")
        f = early.jit("hot", lambda x: x + 1)
        f(jnp.zeros((4,)))
        f.jitted.clear_cache()
        f(jnp.zeros((4,)))
        early.close()
        for _ in range(70):   # > the old deque's maxlen of 64
            tr = RecompileTracer(name="agg-churn")
            tr.jit("g", lambda x: x * 2)(jnp.ones(()))
            tr.close()
        rep = report_all()
        victim = [t for t in rep["tracers"]
                  if t["tracer"] == "agg-victim"]
        assert len(victim) == 1 and victim[0]["closed"]
        assert victim[0]["unexpected_retraces"] == 1
        churn = [t for t in rep["tracers"]
                 if t["tracer"] == "agg-churn"]
        assert len(churn) == 1, "same-name closes fold into ONE row"
        assert churn[0]["closed_tracers"] == 70
        assert churn[0]["sites"]["g"]["traces"] == 70
        assert rep["unexpected_retraces"] >= 1

    def test_engine_gc_retires_tracer(self):
        """Engines register tracers STRONGLY (bench reports outlive
        the engine) — so a collected Engine must retire its tracer or
        repeated construction grows the live set forever."""
        import gc
        from paddle_tpu.observability.trace import all_tracers
        net = paddle.nn.Linear(4, 2)
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        tr = model._engine.tracer
        assert tr in all_tracers()
        del model, net
        gc.collect()
        assert tr not in all_tracers()


# -- profiler bridge ------------------------------------------------------

class TestProfilerBridge:
    def test_record_event_lands_in_registry(self):
        import jax.numpy as jnp
        from paddle_tpu.profiler import Profiler, RecordEvent
        reg = MetricsRegistry()
        p = Profiler(registry=reg).start()
        with p.record_event("region_a"):
            float(jnp.ones((4,)).sum())
        with RecordEvent("region_b", p):
            pass
        p.step()
        p.stop()
        for region in ("region_a", "region_b", "train_step"):
            h = reg.get("profiler_region_seconds",
                        {"region": region})
            assert h is not None and h.count == 1, region

    def test_registry_false_disables_bridge(self):
        from paddle_tpu.profiler import Profiler
        p = Profiler(registry=False).start()
        with p.record_event("quiet", sync=False):
            pass
        p.stop()
        assert p.registry is None

    def test_export_chrome_tracing_copies_artifacts(self, tmp_path):
        from paddle_tpu.profiler import export_chrome_tracing

        class FakeProf:
            trace_dir = str(tmp_path / "trace")
        run = tmp_path / "trace" / "plugins" / "profile" / "run1"
        run.mkdir(parents=True)
        (run / "host.trace.json.gz").write_bytes(b"x")
        (run / "host.xplane.pb").write_bytes(b"y")
        (run / "notes.txt").write_bytes(b"ignored")
        out = tmp_path / "export"
        cb = export_chrome_tracing(str(out), worker_name="w0")
        prof = FakeProf()
        cb(prof)
        names = sorted(os.listdir(out))
        assert names == ["w0.host.trace.json.gz", "w0.host.xplane.pb"]
        assert prof._export_dir == str(out)
        assert len(prof._exported) == 2

    def test_export_disambiguates_same_named_runs(self, tmp_path):
        """Two profiling runs under one trace_dir with same-named
        artifacts must BOTH survive the flat export (the colliding
        copy carries its source subpath in the name)."""
        from paddle_tpu.profiler import export_chrome_tracing

        class FakeProf:
            trace_dir = str(tmp_path / "trace")
        for run in ("run1", "run2"):
            d = tmp_path / "trace" / "plugins" / "profile" / run
            d.mkdir(parents=True)
            (d / "host.xplane.pb").write_bytes(run.encode())
        out = tmp_path / "export"
        prof = FakeProf()
        export_chrome_tracing(str(out))(prof)
        assert len(prof._exported) == 2
        payloads = {open(p, "rb").read() for p in prof._exported}
        assert payloads == {b"run1", b"run2"}


# -- bench worker telemetry (subprocess: the real finalize path) ----------

class TestBenchTelemetry:
    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _run(self, code, argv, env_extra, timeout=120):
        import subprocess
        import sys as _sys
        env = dict(os.environ, CAMPAIGN_CHILD="1", **env_extra)
        return subprocess.run([_sys.executable, "-c", code] + argv,
                              cwd=self.REPO, env=env,
                              capture_output=True, text=True,
                              timeout=timeout)

    def test_probe_worker_telemetry_stays_framework_free(self, tmp_path):
        """The probe's time-to-first-signal measures the backend
        handshake — its telemetry must not charge it the full
        paddle_tpu package import (the stdlib-only observability
        modules are file-loaded instead, bench._obs_mod)."""
        code = (
            "import sys; sys.argv = ['bench.py']\n"
            "import bench, json, os\n"
            "bench._TELEMETRY['worker'] = 'probe'\n"
            "bench.worker_probe()\n"
            "bench._finalize_worker_telemetry('probe')\n"
            "assert 'paddle_tpu' not in sys.modules, 'full import paid'\n"
            "d = os.path.join(bench.CAMPAIGN_OUT, 'telemetry', 'probe')\n"
            "doc = json.load(open(os.path.join(d, 'metrics.json')))\n"
            "assert doc['workers'] == ['probe'], doc\n"
            "print('LEAN-OK')\n")
        proc = self._run(code, [], {"JAX_PLATFORMS": "cpu",
                                    "BENCH_CAMPAIGN_DIR": str(tmp_path)})
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "LEAN-OK" in proc.stdout

    def test_metrics_merge_scoped_to_run_id(self, tmp_path):
        """Cross-worker merge spans ONE bench invocation (shared
        BENCH_RUN_ID); a re-invocation with the same telemetry dir
        OVERWRITES — it must not compound the previous run's counters
        or resurrect its retraces."""
        code = (
            "import sys\n"
            "workers = sys.argv[1:]; sys.argv = ['bench.py']\n"
            "import bench\n"
            "for w in workers:\n"
            "    bench._TELEMETRY.clear()\n"
            "    bench._TELEMETRY['worker'] = w\n"
            "    bench._emit('run_note', worker=w)\n"
            "    bench._finalize_worker_telemetry(w)\n")
        env = {"BENCH_TELEMETRY_DIR": str(tmp_path),
               "BENCH_CAMPAIGN_DIR": str(tmp_path)}
        p = self._run(code, ["w1", "w2"],
                      {**env, "BENCH_RUN_ID": "r1"}, timeout=60)
        assert p.returncode == 0, p.stderr[-2000:]
        doc = json.load(open(tmp_path / "metrics.json"))
        assert doc["workers"] == ["w1", "w2"]   # same-run merge
        p = self._run(code, ["w3"],
                      {**env, "BENCH_RUN_ID": "r2"}, timeout=60)
        assert p.returncode == 0, p.stderr[-2000:]
        doc = json.load(open(tmp_path / "metrics.json"))
        assert doc["workers"] == ["w3"]         # re-invocation overwrote


# =========================================================================
# Round-10 deep-introspection layer (ISSUE 5): compiled-cost capture,
# live exporter, span timelines, crash flight recorder.
# =========================================================================

from paddle_tpu.observability import (exporter as obs_exporter,  # noqa: E402
                                      flightrec, introspect)
from paddle_tpu.observability.spans import (SpanRecorder,  # noqa: E402
                                            export_chrome)


@pytest.fixture(autouse=True)
def _clean_introspection(monkeypatch, tmp_path):
    """Introspection + flight state are process-global; isolate each
    test and point stray dumps at a tmp dir."""
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path / "flight"))
    introspect.clear()
    flightrec.get_recorder().clear()
    yield
    introspect.clear()
    flightrec.get_recorder().clear()


class TestIntrospect:
    def test_normalize_cost_handles_both_jax_shapes(self):
        # jax 0.4.x: list of dicts; 0.6.x: dict; CPU builds may omit keys
        lst = introspect.normalize_cost(
            [{"flops": 10.0, "bytes accessed": 5.0}])
        assert lst == {"flops": 10.0, "bytes_accessed": 5.0,
                       "transcendentals": None}
        dct = introspect.normalize_cost({"flops": 3})
        assert dct["flops"] == 3.0
        assert introspect.normalize_cost(None) is None
        assert introspect.normalize_cost([]) == {
            "flops": None, "bytes_accessed": None,
            "transcendentals": None}
        assert introspect.normalize_cost("bogus") is None

    def test_resolve_peak_env_override_beats_table(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "123e9")
        peak, src = introspect.resolve_peak_flops()
        assert peak == 123e9 and src == "env:PADDLE_TPU_PEAK_FLOPS"

    def test_resolve_peak_table_by_device_kind(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_PEAK_FLOPS", raising=False)
        peak, src = introspect.resolve_peak_flops("TPU v5 lite")
        assert peak == 197e12 and src.startswith("table:")
        peak, src = introspect.resolve_peak_flops("TPU v4")
        assert peak == 275e12
        peak, src = introspect.resolve_peak_flops("Quantum9000")
        assert peak is None and "unknown-device-kind" in src

    def test_resolve_peak_null_on_cpu_without_override(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_PEAK_FLOPS", raising=False)
        peak, src = introspect.resolve_peak_flops()   # CPU backend
        assert peak is None and src == "no-table:cpu"

    def test_measured_mfu_null_honesty(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_PEAK_FLOPS", raising=False)
        assert introspect.measured_mfu(None, 0.1) is None
        assert introspect.measured_mfu(1e9, 0) is None
        assert introspect.measured_mfu(1e9, 0.1) is None  # no peak
        assert introspect.measured_mfu(1e9, 0.1, peak=1e12) == \
            pytest.approx(0.01)

    def test_capture_rides_the_tracer_without_recompile_noise(self):
        """A traced site is introspected exactly once per compile, the
        AOT replay never bumps trace counters, and the capture carries
        real non-zero FLOPs on CPU."""
        import jax.numpy as jnp
        reg = MetricsRegistry()
        tr = RecompileTracer(name="intro_t", registry=reg)
        f = tr.jit("mm", lambda a, b: jnp.dot(a, b) + 1.0)
        a = jnp.ones((16, 16), jnp.float32)
        for _ in range(3):
            f(a, a)
        assert tr._counts["mm"] == 1          # replay stayed silent
        assert tr.unexpected_retraces() == 0
        e = introspect.site_cost("mm", tracer="intro_t")
        assert e is not None and e["captures"] == 1
        if e["flops"] is not None:            # key present on this jax
            assert e["flops"] >= 2 * 16 * 16 * 16
        # registry gauge published under (tracer, site) labels
        g = reg.get("xla_cost_flops",
                    labels={"tracer": "intro_t", "site": "mm"})
        assert (g is None) == (e["flops"] is None)
        rep = introspect.cost_report()
        assert "intro_t/mm" in rep["sites"]
        tr.close()

    def test_compile_budget_skips_with_reason(self):
        out = introspect.capture_site("t", "slow_site", None, (), {},
                                      wall_s=1e9)
        assert out is None
        assert "budget" in introspect.cost_report()["skipped"]["t/slow_site"]

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_INTROSPECT", "0")
        assert not introspect.enabled()
        assert introspect.capture_site("t", "s", None, (), {}) is None
        assert introspect.cost_report()["sites"] == {}

    def test_broken_aot_records_reason_not_raise(self):
        class Boom:
            def lower(self, *a, **k):
                raise RuntimeError("no AOT here")
        out = introspect.capture_site("t", "broken", Boom(), (), {})
        assert out is None
        skipped = introspect.cost_report()["skipped"]
        assert "RuntimeError" in skipped["t/broken"]


def _parse_prom(text):
    """Prometheus text -> {series_key: float} (comments skipped)."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        key, _, val = line.rpartition(" ")
        out[key] = float(val)
    return out


class TestExporter:
    def test_endpoints_roundtrip(self):
        import urllib.error
        import urllib.request
        reg = MetricsRegistry()
        reg.counter("c").inc(7)
        reg.histogram("h", buckets=(1.0,)).observe(0.5)
        ex = obs_exporter.MetricsExporter(
            registry=reg, health_fn=lambda: {"queue": 3},
            report_fn=lambda: {"extra_section": True})
        try:
            txt = urllib.request.urlopen(
                ex.url + "/metrics", timeout=10).read().decode()
            assert txt == reg.to_prometheus()
            h = json.load(urllib.request.urlopen(ex.url + "/healthz",
                                                 timeout=10))
            assert h["status"] == "ok" and h["queue"] == 3
            r = json.load(urllib.request.urlopen(ex.url + "/report",
                                                 timeout=10))
            assert "recompile_report" in r and "cost_report" in r
            assert r["extra_section"] is True
            try:
                urllib.request.urlopen(ex.url + "/nope", timeout=10)
                assert False, "404 expected"
            except urllib.error.HTTPError as e:
                assert e.code == 404
                assert "endpoints" in json.load(e)
        finally:
            ex.close()

    def test_close_releases_port_for_immediate_rebind(self):
        reg = MetricsRegistry()
        ex1 = obs_exporter.MetricsExporter(registry=reg)
        port = ex1.port
        ex1.close()
        ex2 = obs_exporter.MetricsExporter(registry=reg, port=port)
        assert ex2.port == port
        ex2.close()

    def test_double_close_is_idempotent(self):
        ex = obs_exporter.MetricsExporter(registry=MetricsRegistry())
        ex.close()
        ex.close()   # second close: no error, no hang
        with obs_exporter.MetricsExporter(
                registry=MetricsRegistry()) as ex2:
            pass
        ex2.close()  # context exit already closed it

    def test_scrape_after_close_refused(self):
        import urllib.error
        import urllib.request
        ex = obs_exporter.MetricsExporter(registry=MetricsRegistry())
        url = ex.url
        ex.close()
        with pytest.raises((urllib.error.URLError, OSError)):
            urllib.request.urlopen(url + "/metrics", timeout=2)


class TestServeObservability:
    """One 2-request serve wave, scraped live from a second thread:
    pins the span-timeline golden AND the no-torn-histogram scrape
    contract in a single compile."""

    @pytest.fixture(scope="class")
    def wave(self):
        import threading
        import urllib.request
        import paddle_tpu as paddle
        from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
        from paddle_tpu.nlp.serving import ServingEngine

        paddle.seed(0)
        model = GPTForCausalLM(_resolve_config("gpt-tiny",
                                               num_attention_heads=1))
        eng = ServingEngine(model, max_slots=2, page_size=8,
                            max_seq_len=32, steps_per_dispatch=2)
        ex = eng.serve_metrics(port=0)
        rng = np.random.default_rng(0)
        rids = [eng.submit(rng.integers(
            0, model.config.vocab_size, (5 + i,)), max_new_tokens=4)
            for i in range(2)]
        scrapes, stop = [], threading.Event()

        def scraper():
            while not stop.is_set():
                try:
                    scrapes.append(urllib.request.urlopen(
                        ex.url + "/metrics", timeout=10).read().decode())
                except OSError:
                    pass
        t = threading.Thread(target=scraper, daemon=True)
        t.start()
        finished = []
        rounds = 0
        while eng._queue or any(s is not None for s in eng._slots):
            finished.extend(eng.step())
            rounds += 1
            assert rounds < 500
        stop.set()
        t.join(timeout=5)
        final = urllib.request.urlopen(
            ex.url + "/metrics", timeout=10).read().decode()
        data = {"eng": eng, "exporter": ex, "rids": rids,
                "finished": finished, "scrapes": scrapes,
                "final": final,
                "events": eng.spans.events(),
                "prom": eng.registry.to_prometheus()}
        yield data
        eng.close()

    def test_wave_completed_ok(self, wave):
        assert {r["id"] for r in wave["finished"]} == set(wave["rids"])
        assert all(r["status"] == "ok" for r in wave["finished"])

    def test_span_timeline_golden(self, wave):
        """The host-scheduling story for a 2-request wave: each request
        lane tells queue_wait -> prefill_<bucket> -> finish, the shared
        decode lane carries batched dispatches, sched releases pages."""
        by_lane = {}
        for ev in wave["events"]:
            by_lane.setdefault(ev["tid"], []).append(ev)
        for rid in wave["rids"]:
            lane = by_lane[f"req{rid}"]
            names = [e["name"] for e in lane]
            assert names[0] == "queue_wait"
            assert names[1].startswith("prefill_")
            assert names[-1] == "finish"
            assert lane[-1]["args"]["status"] == "ok"
            # spans on one lane are time-ordered
            ts = [e["ts"] for e in lane]
            assert ts == sorted(ts)
        decode = by_lane.get("decode", [])
        assert decode and all(e["name"] == "decode" for e in decode)
        assert sum(e["args"]["tokens"] for e in decode) > 0
        sched = by_lane.get("sched", [])
        assert len([e for e in sched
                    if e["name"] == "release_pages"]) == 2

    def test_chrome_export_merges_lanes(self, wave, tmp_path):
        rec2 = SpanRecorder(name="other")
        rec2.add("x", rec2.now())
        path = export_chrome(str(tmp_path / "tl.json"),
                             [wave["eng"].spans, rec2])
        doc = json.load(open(path))
        evs = doc["traceEvents"]
        pids = {e["pid"] for e in evs}
        assert pids == {1, 2}
        names = {e["args"]["name"] for e in evs
                 if e["name"] == "process_name"}
        assert names == {"serving", "other"}
        # integer tids + thread_name metadata for every named lane
        assert all(isinstance(e["tid"], int) for e in evs)
        lanes = {e["args"]["name"] for e in evs
                 if e["name"] == "thread_name" and e["pid"] == 1}
        assert {"decode", "sched"} <= lanes

    def test_concurrent_scrapes_never_torn(self, wave):
        """Every mid-wave scrape is internally consistent: for each
        histogram, the +Inf bucket equals its _count — a torn read
        (count bumped, bucket not yet) would break this."""
        assert wave["scrapes"], "scraper thread never landed a scrape"
        for txt in wave["scrapes"]:
            vals = _parse_prom(txt)
            counts = {k: v for k, v in vals.items()
                      if k.endswith("_count") and "{" not in k}
            for ck, cv in counts.items():
                base = ck[:-len("_count")]
                inf_key = base + '_bucket{le="+Inf"}'
                if inf_key in vals:
                    assert vals[inf_key] == cv, (ck, txt[:400])

    def test_final_scrape_matches_registry(self, wave):
        assert wave["final"] == wave["prom"]

    def test_engine_close_shuts_exporter(self, wave):
        import urllib.request
        eng = wave["eng"]
        url = wave["exporter"].url
        eng.close()
        with pytest.raises(OSError):
            urllib.request.urlopen(url + "/metrics", timeout=2)


class TestFlightRecorder:
    def test_ring_keeps_last_n_in_arrival_order(self):
        rec = flightrec.FlightRecorder(capacity=4)
        for i in range(10):
            rec.note("step", i=i)
        got = rec.records()
        assert [r["i"] for r in got] == [6, 7, 8, 9]
        assert [r["seq"] for r in got] == [6, 7, 8, 9]

    def test_dump_parses_and_never_clobbers(self, tmp_path):
        rec = flightrec.FlightRecorder(capacity=8,
                                       run_dir=str(tmp_path))
        rec.note("step", loss=float("nan"), i=1)
        p1 = rec.dump("boom", extra={"x": 1})
        p2 = rec.dump("boom")
        assert p1 != p2 and os.path.basename(p1) == "flight_boom.json"
        doc = json.load(open(p1))
        assert doc["reason"] == "boom" and doc["x"] == 1
        assert doc["records"][0]["loss"] is None   # NaN -> null
        assert isinstance(doc.get("registry"), dict)
        assert rec.dumps == [p1, p2]

    def test_reason_sanitized_into_filename(self, tmp_path):
        rec = flightrec.FlightRecorder(run_dir=str(tmp_path))
        p = rec.dump("we/ird reason!")
        assert os.path.basename(p) == "flight_we_ird_reason_.json"

    def test_dump_failure_returns_none(self):
        rec = flightrec.FlightRecorder(
            run_dir="/dev/null/not_a_dir")
        assert rec.dump("x") is None   # never raises

    def test_env_dir_resolution(self, tmp_path, monkeypatch):
        d = tmp_path / "env_dir"
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(d))
        rec = flightrec.FlightRecorder()
        p = rec.dump("envtest")
        assert p is not None and os.path.dirname(p) == str(d)

    def test_serve_step_exception_dumps(self, tmp_path, monkeypatch):
        import paddle_tpu as paddle
        from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
        from paddle_tpu.nlp.serving import ServingEngine
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        paddle.seed(0)
        model = GPTForCausalLM(_resolve_config("gpt-tiny",
                                               num_attention_heads=1))
        eng = ServingEngine(model, max_slots=1, page_size=8,
                            max_seq_len=32)
        monkeypatch.setattr(
            eng, "_step_impl",
            lambda: (_ for _ in ()).throw(RuntimeError("kaboom")))
        with pytest.raises(RuntimeError, match="kaboom"):
            eng.step()
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_serve_exception")]
        assert len(dumps) == 1
        doc = json.load(open(tmp_path / dumps[0]))
        assert "kaboom" in doc["error"]
        eng.close()

    def test_fit_exception_dumps(self, tmp_path, monkeypatch):
        import paddle_tpu as paddle
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        paddle.seed(0)
        net = paddle.nn.Linear(4, 2)
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.AdamW(
            1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        X = np.zeros((8, 4), "float32")
        Y = np.zeros((8,), "int64")

        class BoomCB:
            def __getattr__(self, name):
                return lambda *a, **k: None

            def on_train_batch_end(self, step, logs=None):
                if step == 1:
                    raise RuntimeError("cb boom")
        with pytest.raises(RuntimeError, match="cb boom"):
            model.fit(paddle.io.TensorDataset([X, Y]), epochs=1,
                      batch_size=4, verbose=0, shuffle=False,
                      callbacks=[BoomCB()])
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_fit_exception")]
        assert len(dumps) == 1
        doc = json.load(open(tmp_path / dumps[0]))
        assert "cb boom" in doc["error"]

    def test_guard_rollback_dump_contains_storm_records(
            self, tmp_path, monkeypatch):
        """The acceptance shape: a guard-tripping run leaves a
        parseable flight_rollback.json whose ring holds the rollback
        window's own guard_step records."""
        import paddle_tpu as paddle
        from paddle_tpu.resilience import TrainGuard
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        guard = TrainGuard(snapshot_every=1, rollback_after=3)
        model.prepare(paddle.optimizer.AdamW(
            1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss(), guard=guard)
        rng = np.random.default_rng(0)
        X = rng.standard_normal((24, 8)).astype("float32")
        Y = rng.integers(0, 4, (24,)).astype("int64")
        faults.inject("nan_grads", step=2, count=3)
        model.fit(paddle.io.TensorDataset([X, Y]), epochs=1,
                  batch_size=4, verbose=0, shuffle=False)
        assert guard.rollbacks == 1
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_rollback")]
        assert len(dumps) == 1
        doc = json.load(open(tmp_path / dumps[0]))
        bad = [r for r in doc["records"]
               if r["kind"] == "guard_step" and not r["ok"]]
        assert len(bad) == 3            # the storm's own records
        assert bad[-1]["outcome"] == "rolled_back"
        assert doc["guard"]["rollbacks"] == 1
        assert any(r["kind"] == "guard_rollback"
                   for r in doc["records"])


class TestSpanRecorder:
    def test_bounded_ring_and_clear(self):
        rec = SpanRecorder(maxlen=3)
        for i in range(5):
            rec.instant(f"i{i}")
        assert [e["name"] for e in rec.events()] == ["i2", "i3", "i4"]
        rec.clear()
        assert rec.events() == []

    def test_span_context_manager_and_args(self):
        rec = SpanRecorder()
        with rec.span("work", tid="lane", detail=7):
            pass
        ev = rec.events()[0]
        assert ev["name"] == "work" and ev["ph"] == "X"
        assert ev["args"] == {"detail": 7} and ev["dur"] >= 0

    def test_recorders_share_one_clock(self, tmp_path):
        a, b = SpanRecorder(name="a"), SpanRecorder(name="b")
        t = SpanRecorder.now()
        a.add("first", t, t + 0.001)
        b.add("second", t + 0.002, t + 0.003)
        path = export_chrome(str(tmp_path / "m.json"), [a, b])
        evs = [e for e in json.load(open(path))["traceEvents"]
               if e["ph"] == "X"]
        assert evs[0]["name"] == "first"    # cross-recorder ordering
        assert evs[1]["ts"] > evs[0]["ts"]

    def test_profiler_regions_land_on_span_bridge(self):
        from paddle_tpu.profiler import Profiler, RecordEvent
        prof = Profiler(registry=False)
        with prof.record_event("regionA", sync=False):
            pass
        with RecordEvent("regionB", profiler=prof):
            pass
        names = [e["name"] for e in prof.spans.events()]
        assert names == ["regionA", "regionB"]
        assert all(e["tid"] == "regions"
                   for e in prof.spans.events())


class TestMeasuredMFUGauges:
    def test_callback_publishes_measured_mfu(self, tmp_path,
                                             monkeypatch):
        import paddle_tpu as paddle
        from paddle_tpu.observability.telemetry import TelemetryCallback
        # a small peak so the tiny model's MFU survives the JSONL
        # rounding (the gauges are unrounded either way)
        monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "1e8")
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.AdamW(
            1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        X = np.random.default_rng(0).standard_normal(
            (16, 8)).astype("float32")
        Y = np.random.default_rng(0).integers(0, 4, (16,)).astype("int64")
        reg = MetricsRegistry()
        cb = TelemetryCallback(run_dir=str(tmp_path), registry=reg,
                               write_metrics=False,
                               flops_per_step=2 * 8 * 4 * 4 * 3)
        model.fit(paddle.io.TensorDataset([X, Y]), epochs=1,
                  batch_size=4, verbose=0, shuffle=False,
                  callbacks=[cb])
        assert reg.get("train_peak_flops").value == 1e8
        m = reg.get("train_mfu_measured")
        assert m is not None and 0 < m.value < 1
        a = reg.get("train_mfu_analytic")
        assert a is not None and 0 < a.value < 1
        # JSONL records carry both legs
        recs = [r for r in cb.logger.iter_records()
                if r["kind"] == "train_step"]
        assert recs and recs[-1]["mfu_measured"] > 0
        # spans export landed next to the jsonl
        assert cb.spans_path and os.path.exists(cb.spans_path)

    def test_mfu_gauges_absent_without_peak(self, tmp_path,
                                            monkeypatch):
        import paddle_tpu as paddle
        from paddle_tpu.observability.telemetry import TelemetryCallback
        monkeypatch.delenv("PADDLE_TPU_PEAK_FLOPS", raising=False)
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.AdamW(
            1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        X = np.zeros((8, 8), "float32")
        Y = np.zeros((8,), "int64")
        reg = MetricsRegistry()
        cb = TelemetryCallback(run_dir=str(tmp_path), registry=reg,
                               write_metrics=False)
        model.fit(paddle.io.TensorDataset([X, Y]), epochs=1,
                  batch_size=4, verbose=0, shuffle=False,
                  callbacks=[cb])
        # no resolvable peak on CPU -> honest absence, not a made-up 0
        assert reg.get("train_mfu_measured") is None
        assert reg.get("train_mfu_analytic") is None


class TestMetricsDiffTool:
    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _dump(self, path, fill):
        reg = MetricsRegistry()
        fill(reg)
        reg.dump(str(path))
        return str(path)

    def _run(self, *argv):
        import subprocess
        import sys as _sys
        return subprocess.run(
            [_sys.executable, "tools/metrics_diff.py", *argv],
            cwd=self.REPO, capture_output=True, text=True, timeout=60)

    def test_diff_reports_deltas_added_removed(self, tmp_path):
        a = self._dump(tmp_path / "a.json", lambda r: (
            r.counter("steps").inc(10), r.gauge("gone").set(1)))
        b = self._dump(tmp_path / "b.json", lambda r: (
            r.counter("steps").inc(13), r.gauge("fresh").set(2)))
        p = self._run(a, b)
        assert p.returncode == 0, p.stderr[-1000:]
        rep = json.loads(p.stdout.strip().splitlines()[-1])
        assert rep["ok"] is True
        assert rep["counters"]["steps"]["delta"] == 3
        assert rep["added"] == ["fresh"] and rep["removed"] == ["gone"]

    def test_fail_on_quantile_regression(self, tmp_path):
        def fast(r):
            h = r.histogram("lat", buckets=(0.001, 0.01, 0.1))
            for _ in range(10):
                h.observe(0.002)

        def slow(r):
            h = r.histogram("lat", buckets=(0.001, 0.01, 0.1))
            for _ in range(10):
                h.observe(0.05)
        a = self._dump(tmp_path / "a.json", fast)
        b = self._dump(tmp_path / "b.json", slow)
        p = self._run(a, b, "--fail-on", "lat:p99>10%")
        assert p.returncode == 1
        rep = json.loads(p.stdout.strip().splitlines()[-1])
        assert not rep["ok"]
        assert rep["failures"][0]["series"] == "lat"
        # reversed direction: improvement passes the same gate
        p = self._run(b, a, "--fail-on", "lat:p99>10%")
        assert p.returncode == 0

    def test_fail_on_counter_increase_and_throughput_drop(
            self, tmp_path):
        a = self._dump(tmp_path / "a.json", lambda r: (
            r.counter("retraces").inc(0), r.gauge("tok_s").set(100)))
        b = self._dump(tmp_path / "b.json", lambda r: (
            r.counter("retraces").inc(1), r.gauge("tok_s").set(80)))
        p = self._run(a, b, "--fail-on", "retraces>0%",
                      "--fail-on", "tok_s<10%")
        assert p.returncode == 1
        rep = json.loads(p.stdout.strip().splitlines()[-1])
        assert {f["series"] for f in rep["failures"]} == \
            {"retraces", "tok_s"}

    def test_bad_spec_is_an_argparse_error(self, tmp_path):
        a = self._dump(tmp_path / "a.json", lambda r: None)
        p = self._run(a, a, "--fail-on", "nonsense")
        assert p.returncode == 2
        assert "grammar" in p.stderr


class TestValidateStagesFlightCheck:
    """check_flight_dumps: the preflight gate that chaos-family
    campaign stages actually left their post-mortem dumps."""

    @pytest.fixture()
    def vs(self, tmp_path, monkeypatch):
        import sys as _sys
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        monkeypatch.syspath_prepend(os.path.join(repo, "tools"))
        monkeypatch.syspath_prepend(repo)
        import validate_stages as mod
        monkeypatch.setattr(mod, "OUT", str(tmp_path))
        return mod

    def _summary(self, vs, doc):
        with open(os.path.join(vs.OUT, "summary.json"), "w") as f:
            json.dump(doc, f)

    def test_pre_flightrec_archives_not_flagged(self, vs):
        assert vs.check_flight_dumps() == ([], 0)   # no summary
        self._summary(vs, {"_telemetry": 1,
                           "chaos_smoke": {"ok": True}})
        assert vs.check_flight_dumps() == ([], 0)   # no _flightrec

    def test_completed_chaos_stage_without_dump_is_a_problem(self, vs):
        self._summary(vs, {"_flightrec": 1,
                           "chaos_smoke": {"ok": True},
                           "telemetry_smoke": {"ok": False}})
        problems, checked = vs.check_flight_dumps()
        assert checked == 1                       # failed stage skipped
        assert "left no flight_" in problems[0]

    def test_parseable_dump_passes_torn_dump_fails(self, vs):
        self._summary(vs, {"_flightrec": 1,
                           "chaos_smoke": {"ok": True}})
        td = os.path.join(vs.OUT, "telemetry", "chaos_smoke")
        os.makedirs(td)
        with open(os.path.join(td, "flight_rollback.json"), "w") as f:
            json.dump({"reason": "rollback",
                       "records": [{"kind": "guard_step"}]}, f)
        assert vs.check_flight_dumps() == ([], 1)
        with open(os.path.join(td, "flight_torn.json"), "w") as f:
            f.write("{torn")
        problems, _ = vs.check_flight_dumps()
        assert "unparseable flight dump" in problems[0]


class TestValidateStagesCanaryCheck:
    """check_canary_verdict: a _fleet_canary-marked campaign whose
    fleet_chaos_smoke completed must carry the metrics_diff gate's
    verdict file (ISSUE 8 — the gate must not silently never run)."""

    @pytest.fixture()
    def vs(self, tmp_path, monkeypatch):
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        monkeypatch.syspath_prepend(os.path.join(repo, "tools"))
        monkeypatch.syspath_prepend(repo)
        import validate_stages as mod
        monkeypatch.setattr(mod, "OUT", str(tmp_path))
        return mod

    def _summary(self, vs, doc):
        with open(os.path.join(vs.OUT, "summary.json"), "w") as f:
            json.dump(doc, f)

    def test_pre_gate_archives_and_unrun_stages_not_flagged(self, vs):
        assert vs.check_canary_verdict() == ([], 0)   # no summary
        self._summary(vs, {"fleet_chaos_smoke": {"ok": True, "rc": 0}})
        assert vs.check_canary_verdict() == ([], 0)   # no marker
        self._summary(vs, {"_fleet_canary": 1})
        assert vs.check_canary_verdict() == ([], 0)   # never ran

    def test_completed_stage_without_verdict_is_a_problem(self, vs):
        self._summary(vs, {"_fleet_canary": 1,
                           "fleet_chaos_smoke": {"ok": True, "rc": 0}})
        problems, checked = vs.check_canary_verdict()
        assert checked == 1 and "no verdict" in problems[0]

    def test_parseable_verdict_passes_torn_or_flagless_fails(self, vs):
        self._summary(vs, {"_fleet_canary": 1,
                           "fleet_chaos_smoke": {"ok": True, "rc": 0}})
        td = os.path.join(vs.OUT, "telemetry", "fleet_chaos_smoke")
        os.makedirs(td)
        vp = os.path.join(td, "canary_verdict.json")
        with open(vp, "w") as f:
            json.dump({"ok": True, "failures": []}, f)
        assert vs.check_canary_verdict() == ([], 1)
        with open(vp, "w") as f:
            json.dump({"failures": []}, f)   # no ok flag
        problems, _ = vs.check_canary_verdict()
        assert "no 'ok' flag" in problems[0]
        with open(vp, "w") as f:
            f.write("{torn")
        problems, _ = vs.check_canary_verdict()
        assert "unparseable canary verdict" in problems[0]


class TestGuardOutcomeAfterRollback:
    def test_storm_outlasting_rollback_keeps_skipping_one_dump(
            self, tmp_path, monkeypatch):
        """Review regression: a storm LONGER than rollback_after must
        report the post-rollback bad steps as 'skipped' (consecutive
        count restarted) and dump exactly one flight record — not
        re-report 'rolled_back' and re-dump every further bad step."""
        import paddle_tpu as paddle
        from paddle_tpu.resilience import TrainGuard
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        guard = TrainGuard(snapshot_every=1, rollback_after=3)
        model.prepare(paddle.optimizer.AdamW(
            1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss(), guard=guard)
        rng = np.random.default_rng(0)
        X = rng.standard_normal((32, 8)).astype("float32")
        Y = rng.integers(0, 4, (32,)).astype("int64")
        faults.inject("nan_grads", step=2, count=4)   # 4-step storm
        model.fit(paddle.io.TensorDataset([X, Y]), epochs=1,
                  batch_size=4, verbose=0, shuffle=False)
        assert guard.rollbacks == 1
        assert guard.skipped_steps == 4
        assert guard.last_outcome == "ok"     # recovered after storm
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("flight_rollback")]
        assert len(dumps) == 1                # ONE dump, not per step
        doc = json.load(open(tmp_path / dumps[0]))
        outcomes = [r["outcome"] for r in doc["records"]
                    if r["kind"] == "guard_step" and not r["ok"]]
        assert outcomes == ["skipped", "skipped", "rolled_back"]
        # the 4th bad step (after the dump) went back to 'skipped'
        ring = flightrec.get_recorder().records()
        post = [r for r in ring if r["kind"] == "guard_step"
                and not r["ok"]][-1]
        assert post["outcome"] == "skipped"
