"""Observability subsystem (ISSUE 4): metrics registry, recompile
tracer, structured run telemetry, and their wiring into hapi / serving
/ dataloader / profiler.

Pins the contracts docs/observability.md documents:
- histogram bucket math (log-spaced 1-2-5 ladder, count-weighted
  observe, bucket-interpolated quantiles) and snapshot MERGE;
- Prometheus-text and JSON export golden strings;
- RecompileTracer: an intentional shape change is a trace with a fresh
  signature (expected), re-tracing a seen signature is UNEXPECTED, and
  a zero-recompile serve wave records nothing after warmup;
- TelemetryCallback: skip/rollback counts consistent with TrainGuard
  under an injected NaN storm (resilience.faults seams);
- TelemetryLogger JSONL rotation + torn-line-tolerant summarize();
- ServingEngine health()/reset_counters() uniform reset through the
  registry (the retry/watchdog-survives-reset divergence, fixed).
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.observability.metrics import (Counter, Gauge, Histogram,
                                              MetricsRegistry,
                                              default_time_buckets,
                                              get_registry)
from paddle_tpu.observability.telemetry import (TelemetryCallback,
                                                TelemetryLogger)
from paddle_tpu.observability.trace import RecompileTracer, report_all
from paddle_tpu.resilience import TrainGuard, faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# -- histogram math -------------------------------------------------------

class TestHistogram:
    def test_default_buckets_are_125_ladder(self):
        b = default_time_buckets(-2, 0)
        assert b == (0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0)
        assert list(b) == sorted(b)

    def test_observe_bucketing_and_overflow(self):
        h = Histogram("h", buckets=(1.0, 2.0, 5.0))
        for v in (0.5, 1.0, 1.5, 4.0, 100.0):
            h.observe(v)
        # counts: (..1], (1..2], (2..5], overflow
        assert h.counts == [2, 1, 1, 1]
        assert h.count == 5
        assert h.min == 0.5 and h.max == 100.0
        assert h.sum == pytest.approx(107.0)

    def test_count_weighted_observe(self):
        h = Histogram("h", buckets=(1.0, 10.0))
        h.observe(0.25, count=8)   # a K-token dispatch in O(1)
        assert h.count == 8
        assert h.counts == [8, 0, 0]
        assert h.sum == pytest.approx(2.0)
        assert h.mean() == pytest.approx(0.25)

    def test_quantiles_interpolate_within_min_max(self):
        h = Histogram("h", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.6, 3.0):
            h.observe(v)
        assert h.quantile(0.0) >= h.min
        assert h.quantile(1.0) == pytest.approx(h.max)
        p50 = h.quantile(0.5)
        assert 1.0 <= p50 <= 2.0, "median sits in the (1,2] bucket"
        assert Histogram("e").quantile(0.5) is None

    def test_merge_adds_buckets_and_tracks_extrema(self):
        a = Histogram("h", buckets=(1.0, 2.0))
        b = Histogram("h", buckets=(1.0, 2.0))
        a.observe(0.5)
        b.observe(1.5)
        b.observe(9.0)
        a.merge(b.snapshot())
        assert a.count == 3
        assert a.counts == [1, 1, 1]
        assert a.min == 0.5 and a.max == 9.0
        assert a.sum == pytest.approx(11.0)

    def test_merge_rejects_mismatched_bounds(self):
        a = Histogram("h", buckets=(1.0, 2.0))
        b = Histogram("h", buckets=(1.0, 2.0, 4.0))
        with pytest.raises(ValueError, match="mismatched bucket"):
            a.merge(b.snapshot())


# -- registry: series identity, merge, reset ------------------------------

class TestRegistry:
    def test_series_identity_and_type_guard(self):
        reg = MetricsRegistry()
        c1 = reg.counter("req", labels={"status": "ok"})
        c2 = reg.counter("req", labels={"status": "ok"})
        c3 = reg.counter("req", labels={"status": "bad"})
        assert c1 is c2 and c1 is not c3
        with pytest.raises(TypeError, match="already registered"):
            reg.gauge("req", labels={"status": "ok"})

    def test_merge_counters_add_gauges_last_win(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("n").inc(2)
        b.counter("n").inc(3)
        a.gauge("g").set(1.0)
        b.gauge("g").set(7.0)
        b.histogram("h", buckets=(1.0,)).observe(0.5)
        a.merge(b.snapshot())
        assert a.counter("n").value == 5
        assert a.gauge("g").value == 7.0
        assert a.get("h").count == 1

    def test_reset_zeroes_in_place(self):
        reg = MetricsRegistry()
        c = reg.counter("n")
        h = reg.histogram("h", buckets=(1.0,))
        c.inc(4)
        h.observe(0.5)
        reg.reset()
        assert c.value == 0, "the held handle must stay live"
        assert h.count == 0 and h.min is None

    def test_concurrent_scrape_during_registration(self):
        # a scrape thread iterating the registry while the main thread
        # lazily registers new series must not crash with "dictionary
        # changed size during iteration"
        import threading
        reg = MetricsRegistry()
        stop = threading.Event()
        errs = []

        def scrape():
            while not stop.is_set():
                try:
                    reg.to_prometheus()
                    reg.snapshot()
                    reg.names()
                except Exception as e:  # pragma: no cover
                    errs.append(e)
                    return

        t = threading.Thread(target=scrape)
        t.start()
        try:
            for i in range(300):
                reg.counter("c", labels={"i": str(i)}).inc()
                reg.histogram("h", labels={"i": str(i)}).observe(0.1)
        finally:
            stop.set()
            t.join()
        assert not errs, errs

    def test_dump_is_parseable_with_extra(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("n").inc()
        p = reg.dump(str(tmp_path / "metrics.json"),
                     extra={"recompile_report": {"unexpected": 0}})
        doc = json.loads(open(p).read())
        assert doc["metrics"]["n"]["value"] == 1
        assert doc["recompile_report"] == {"unexpected": 0}


# -- export golden strings ------------------------------------------------

class TestExports:
    def _golden_registry(self):
        reg = MetricsRegistry()
        reg.counter("requests_total", help="served requests",
                    labels={"status": "ok"}).inc(3)
        reg.gauge("free_pages").set(5)
        h = reg.histogram("latency_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5, count=2)
        return reg

    def test_prometheus_golden(self):
        text = self._golden_registry().to_prometheus()
        assert text == (
            "# TYPE free_pages gauge\n"
            "free_pages 5\n"
            "# TYPE latency_seconds histogram\n"
            'latency_seconds_bucket{le="0.1"} 1\n'
            'latency_seconds_bucket{le="1.0"} 3\n'
            'latency_seconds_bucket{le="+Inf"} 3\n'
            "latency_seconds_sum 1.05\n"
            "latency_seconds_count 3\n"
            "# HELP requests_total served requests\n"
            "# TYPE requests_total counter\n"
            'requests_total{status="ok"} 3\n')

    def test_json_golden_roundtrip(self):
        doc = json.loads(self._golden_registry().to_json())
        m = doc["metrics"]
        assert m['requests_total{status="ok"}'] == {
            "name": "requests_total", "labels": {"status": "ok"},
            "type": "counter", "value": 3}
        assert m["latency_seconds"]["counts"] == [1, 2, 0]
        assert m["latency_seconds"]["sum"] == pytest.approx(1.05)
        fresh = MetricsRegistry()
        fresh.merge(doc)   # a dumped snapshot is a mergeable snapshot
        assert fresh.get("free_pages").value == 5


# -- recompile tracer -----------------------------------------------------

class TestRecompileTracer:
    def test_trace_once_then_silent(self):
        import jax.numpy as jnp
        reg = MetricsRegistry()
        tr = RecompileTracer(name="t", registry=reg)
        f = tr.jit("add", lambda x: x + 1)
        for _ in range(3):
            f(jnp.zeros((4,)))
        assert tr.counts() == {"add": 1}
        assert tr.unexpected_retraces() == 0
        [e] = tr.events()
        assert e["site"] == "add" and not e["unexpected"]
        assert "[4]" in e["signature"] and "float" in e["signature"]
        assert reg.counter("recompile_traces_total",
                           labels={"tracer": "t",
                                   "site": "add"}).value == 1

    def test_shape_change_is_expected_new_signature(self):
        import jax.numpy as jnp
        tr = RecompileTracer(name="t")
        f = tr.jit("add", lambda x: x + 1)
        f(jnp.zeros((4,)))
        f(jnp.zeros((8,)))   # intentional retrace: NEW signature
        assert tr.counts()["add"] == 2
        assert tr.unexpected_retraces() == 0
        rep = tr.report()
        assert rep["sites"]["add"] == {"traces": 2, "signatures": 2,
                                       "unexpected_retraces": 0}

    def test_seen_signature_retrace_is_unexpected(self):
        import jax.numpy as jnp
        tr = RecompileTracer(name="t", registry=MetricsRegistry())
        f = tr.jit("add", lambda x: x + 1)
        f(jnp.zeros((4,)))
        # drop THIS function's compiled program (the cliff), without
        # jax.clear_caches() nuking other tests' warm programs
        f.jitted.clear_cache()
        f(jnp.zeros((4,)))
        assert tr.counts()["add"] == 2
        assert tr.unexpected_retraces() == 1
        assert [e["unexpected"] for e in tr.events()] == [False, True]

    def test_report_all_merges_live_tracers(self):
        import jax.numpy as jnp
        tr = RecompileTracer(name="zz-report-all-test")
        tr.jit("f", lambda x: x * 2)(jnp.ones(()))
        rep = report_all()
        names = [t["tracer"] for t in rep["tracers"]]
        assert "zz-report-all-test" in names

    def test_serve_wave_traces_warmup_only(self, tmp_path):
        """The acceptance shape: a zero-recompile serve wave records
        warmup traces and NOTHING after — and the instrumentation
        itself (histograms, health snapshots) induces no retrace."""
        from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
        from paddle_tpu.nlp.serving import ServingEngine
        paddle.seed(0)
        model = GPTForCausalLM(_resolve_config("gpt-tiny"))
        reg = MetricsRegistry()
        eng = ServingEngine(model, max_slots=2, page_size=16,
                            max_seq_len=48, steps_per_dispatch=2,
                            registry=reg)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 256, (6,)) for _ in range(4)]
        eng.generate(prompts, max_new_tokens=4)      # warmup wave
        events_after_warmup = len(eng.tracer.events())
        eng.reset_counters()
        eng.generate(prompts, max_new_tokens=4)      # steady wave
        eng.health()
        assert len(eng.tracer.events()) == events_after_warmup, \
            "steady-state wave must record zero trace events"
        assert eng.tracer.unexpected_retraces() == 0
        assert reg.get("serve_ttft_seconds").count == 4
        assert reg.get("serve_decode_token_seconds").count > 0


# -- telemetry logger: JSONL + rotation -----------------------------------

class TestTelemetryLogger:
    def test_emit_and_summarize(self, tmp_path):
        lg = TelemetryLogger(str(tmp_path))
        lg.emit("train_step", step=1, loss=2.0)
        lg.emit("train_step", step=2, loss=1.0)
        lg.emit("serve_request", ttft_ms=5.0)
        s = lg.summarize()
        assert s["records"] == 3
        st = s["by_kind"]["train_step"]["fields"]["loss"]
        assert st == {"min": 1.0, "max": 2.0, "last": 1.0, "mean": 1.5}
        lg.close()

    def test_rotation_keeps_bounded_files(self, tmp_path):
        lg = TelemetryLogger(str(tmp_path), rotate_bytes=200,
                             max_rotated=2)
        for i in range(50):
            lg.emit("r", i=i, pad="x" * 40)
        assert lg.rotations >= 3
        lg.flush()
        files = lg.files()
        assert [os.path.basename(f) for f in files] == [
            "telemetry.jsonl.2", "telemetry.jsonl.1",
            "telemetry.jsonl"]
        recs = list(lg.iter_records())
        assert recs, "retained files must still parse"
        # newest record survives; the oldest rotated out
        assert recs[-1]["i"] == 49
        assert recs[0]["i"] > 0
        lg.close()

    def test_nan_loss_emits_valid_json(self, tmp_path):
        """A NaN loss (the storm the guard records) must land as RFC
        JSON (null), never a bare NaN token jq/JS consumers reject."""
        lg = TelemetryLogger(str(tmp_path))
        lg.emit("train_step", loss=float("nan"), step_time_s=0.1,
                nested={"g": float("inf")})
        lg.close()
        raw = open(lg.path).read()
        assert "NaN" not in raw and "Infinity" not in raw
        rec = json.loads(raw.splitlines()[0])
        assert rec["loss"] is None and rec["nested"]["g"] is None
        assert rec["step_time_s"] == 0.1

    def test_nan_gauge_dumps_valid_json(self, tmp_path):
        reg = MetricsRegistry()
        reg.gauge("train_loss").set(float("nan"))
        reg.counter("ok_total").inc(2)
        path = reg.dump(str(tmp_path / "metrics.json"))
        raw = open(path).read()
        assert "NaN" not in raw
        doc = json.loads(raw)
        assert doc["metrics"]["train_loss"]["value"] is None
        assert reg.to_json()  # parseable too
        assert "NaN" not in reg.to_json()

    def test_torn_line_does_not_kill_rollup(self, tmp_path):
        lg = TelemetryLogger(str(tmp_path))
        lg.emit("r", i=1)
        lg.flush()
        with open(lg.path, "a") as f:
            f.write('{"kind": "r", "i": 2')   # torn crash write
        assert lg.summarize()["records"] == 1
        lg.close()


# -- TelemetryCallback under a NaN storm ----------------------------------

class TestTelemetryCallback:
    def _fit(self, tmp_path, registry, storm=None):
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        guard = TrainGuard(snapshot_every=1, rollback_after=3)
        model.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss(), guard=guard)
        rng = np.random.default_rng(0)
        X = rng.standard_normal((32, 8)).astype("float32")
        Y = rng.integers(0, 4, (32,)).astype("int64")
        cb = TelemetryCallback(run_dir=str(tmp_path), registry=registry)
        if storm:
            faults.inject("nan_grads", step=storm[0], count=storm[1])
        model.fit(paddle.io.TensorDataset([X, Y]), epochs=1,
                  batch_size=4, verbose=0, shuffle=False,
                  callbacks=[cb])
        return guard, cb

    def test_storm_counts_match_guard(self, tmp_path):
        reg = MetricsRegistry()
        guard, cb = self._fit(tmp_path, reg, storm=(3, 3))
        assert guard.skipped_steps == 3
        assert guard.rollbacks == 1
        assert reg.counter("train_skipped_steps_total").value == 3
        assert reg.counter("train_rollbacks_total").value == 1
        assert reg.counter("train_steps_total").value == 8
        assert reg.get("train_step_seconds").count == 8
        assert reg.gauge("train_loss").value > 0
        assert reg.gauge("train_samples_per_s").value > 0
        assert reg.gauge("train_grad_norm").value >= 0
        # JSONL records carry the same story, step by step
        recs = [r for r in cb.logger.iter_records()
                if r["kind"] == "train_step"]
        assert len(recs) == 8
        assert [r["outcome"] for r in recs] == (
            ["ok", "ok", "skipped", "skipped", "rolled_back",
             "ok", "ok", "ok"])
        assert recs[-1]["skipped"] == 3 and recs[-1]["rollbacks"] == 1
        end = [r for r in cb.logger.iter_records()
               if r["kind"] == "train_end"]
        assert end and end[0]["skipped_steps"] == 3

    def test_clean_run_exports_zero_counters(self, tmp_path):
        """A clean run exports the guard counters AT ZERO — absent
        metrics are indistinguishable from broken wiring."""
        reg = MetricsRegistry()
        guard, cb = self._fit(tmp_path, reg)
        assert reg.counter("train_skipped_steps_total").value == 0
        assert reg.counter("train_rollbacks_total").value == 0
        assert cb.metrics_path and os.path.exists(cb.metrics_path)
        doc = json.load(open(cb.metrics_path))
        assert "recompile_report" in doc
        # scope to THIS fit's engine: report_all() spans every tracer
        # the process ever made, including other tests' deliberate
        # retraces (tracers register strongly — see trace.py)
        assert cb.model._engine.tracer.unexpected_retraces() == 0

    def test_second_fit_does_not_recount_history(self, tmp_path):
        """Guard/scaler totals are lifetime-absolute on the guard; a
        second fit() on the same model must baseline them at
        train_begin and diff only ITS OWN skips into the registry."""
        reg = MetricsRegistry()
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        guard = TrainGuard(snapshot_every=1, rollback_after=3)
        model.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss(), guard=guard)
        rng = np.random.default_rng(0)
        X = rng.standard_normal((32, 8)).astype("float32")
        Y = rng.integers(0, 4, (32,)).astype("int64")
        ds = paddle.io.TensorDataset([X, Y])
        faults.inject("nan_grads", step=3, count=3)
        model.fit(ds, epochs=1, batch_size=4, verbose=0, shuffle=False,
                  callbacks=[TelemetryCallback(run_dir=str(tmp_path),
                                               registry=reg)])
        assert guard.skipped_steps == 3
        assert reg.counter("train_skipped_steps_total").value == 3
        # clean second fit: fresh callback, same guard + registry —
        # the counters must NOT double to 6/2
        model.fit(ds, epochs=1, batch_size=4, verbose=0, shuffle=False,
                  callbacks=[TelemetryCallback(run_dir=str(tmp_path),
                                               registry=reg)])
        assert guard.skipped_steps == 3
        assert reg.counter("train_skipped_steps_total").value == 3
        assert reg.counter("train_rollbacks_total").value == 1
        assert reg.counter("train_steps_total").value == 16

    def test_grad_norm_is_opt_in(self, tmp_path):
        """A bare Engine (no TelemetryCallback) must not pay the
        in-step grad-norm reduction: last_grad_norm stays None and the
        compiled step matches pre-telemetry baselines. With the
        callback attached, the same step exports a real norm."""
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        eng = model._engine
        assert not eng.collect_grad_norm
        x = np.zeros((4, 8), dtype="float32")
        y = np.zeros((4,), dtype="int64")
        model.train_batch([x], [y])
        assert eng.last_grad_norm is None

        reg = MetricsRegistry()
        guard, cb = self._fit(tmp_path, reg)
        recs = [r for r in cb.logger.iter_records()
                if r["kind"] == "train_step"]
        assert all(r.get("grad_norm") is not None for r in recs)
        assert cb.model._engine.collect_grad_norm

    def test_grad_norm_cleared_on_accum_and_multi_paths(self):
        """train_batch_accum / train_batch_multi compute no global
        grad norm; they must CLEAR last_grad_norm so a later telemetry
        read never reports a stale fused-step value as current."""
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        eng = model._engine
        eng.enable_grad_norm()
        x = np.zeros((4, 8), dtype="float32")
        y = np.zeros((4,), dtype="int64")
        model.train_batch([x], [y])
        assert eng.last_grad_norm is not None
        eng.train_batch_accum([x], [y], apply_update=True)
        assert eng.last_grad_norm is None

        model.train_batch([x], [y])
        assert eng.last_grad_norm is not None
        xs = np.stack([x, x])
        ys = np.stack([y, y])
        eng.train_batch_multi([xs], [ys])
        assert eng.last_grad_norm is None

    def test_dataloader_batch_wait_lands_in_global_registry(self):
        from paddle_tpu.io import DataLoader, TensorDataset
        reg = get_registry()
        train = {"role": "train"}
        before = reg.get("dataloader_batches_total", labels=train)
        before = before.value if before else 0
        X = np.zeros((8, 3), "float32")
        n = sum(1 for _ in DataLoader(TensorDataset([X]), batch_size=2))
        assert n == 4
        assert reg.counter("dataloader_batches_total",
                           labels=train).value == before + 4
        assert reg.get("dataloader_batch_wait_seconds",
                       labels=train).count >= 4

    def test_dataloader_role_label_separates_eval_from_train(self):
        # eval/predict loaders must not pollute the train batch-wait
        # series (the input-bound-run diagnostic)
        from paddle_tpu.io import DataLoader, TensorDataset
        reg = get_registry()
        train = reg.counter("dataloader_batches_total",
                            labels={"role": "train"}).value
        X = np.zeros((6, 3), "float32")
        loader = DataLoader(TensorDataset([X]), batch_size=2)
        loader._obs_role = "eval"
        assert sum(1 for _ in loader) == 3
        assert reg.counter("dataloader_batches_total",
                           labels={"role": "eval"}).value >= 3
        assert reg.counter("dataloader_batches_total",
                           labels={"role": "train"}).value == train


# -- serving reset/health uniformity (the ISSUE 4 divergence fix) ---------

class TestServeResetUniformity:
    @pytest.fixture(scope="class")
    def engine(self):
        from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
        from paddle_tpu.nlp.serving import ServingEngine
        paddle.seed(0)
        model = GPTForCausalLM(_resolve_config("gpt-tiny"))
        eng = ServingEngine(model, max_slots=2, page_size=16,
                            max_seq_len=48, steps_per_dispatch=2,
                            dispatch_retries=2,
                            registry=MetricsRegistry())
        yield eng
        eng.close()

    def test_reset_clears_retry_and_status_fields(self, engine):
        rng = np.random.default_rng(0)
        faults.inject("dispatch_error", count=1)
        engine.generate([rng.integers(0, 256, (6,))], max_new_tokens=4)
        h = engine.health()
        assert h["dispatch_retries"] == 1
        assert h["status_counts"]["ok"] == 1
        assert h["deadline_misses"] == 0
        engine.reset_counters()
        h2 = engine.health()
        assert h2["dispatch_retries"] == 0, \
            "retry count must not survive reset_counters()"
        assert h2["status_counts"]["ok"] == 0
        assert h2["decode_tokens"] == 0
        # live state (pages, queue) is NOT a counter: still truthful
        assert h2["free_pages"] == engine.free_page_count

    def test_counters_resume_after_reset(self, engine):
        rng = np.random.default_rng(1)
        engine.generate([rng.integers(0, 256, (6,))], max_new_tokens=4)
        h = engine.health()
        assert h["status_counts"]["ok"] == 1
        assert h["page_occupancy"] == 0.0, "drained pool reads empty"


class TestServeRegistryIsolation:
    def test_default_registries_are_per_engine(self):
        """Two engines with the default registry must not alias each
        other's serve_* series: counts stay per-engine and one
        engine's reset cannot zero a sibling's window."""
        from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
        from paddle_tpu.nlp.serving import ServingEngine
        from paddle_tpu.observability.metrics import get_registry
        paddle.seed(0)
        model = GPTForCausalLM(_resolve_config("gpt-tiny"))
        a = ServingEngine(model, max_slots=1, page_size=16,
                          max_seq_len=48, steps_per_dispatch=2)
        b = ServingEngine(model, max_slots=1, page_size=16,
                          max_seq_len=48, steps_per_dispatch=2)
        try:
            assert a.registry is not b.registry
            assert a.registry is not get_registry()
            rng = np.random.default_rng(0)
            a.generate([rng.integers(0, 256, (6,))], max_new_tokens=4)
            assert a.health()["status_counts"]["ok"] == 1
            assert b.health()["status_counts"]["ok"] == 0
            b.reset_counters()
            assert a.health()["status_counts"]["ok"] == 1, \
                "a sibling's reset_counters() must not zero this engine"
        finally:
            a.close()
            b.close()

    def test_closed_tracer_report_retained(self):
        """close() deregisters the tracer (no unbounded growth across
        engine reloads) but its site aggregates stay in report_all."""
        from paddle_tpu.observability.trace import (RecompileTracer,
                                                    all_tracers,
                                                    report_all)
        tr = RecompileTracer(name="retired", registry=MetricsRegistry())
        f = tr.jit("square", lambda x: x * x)
        f(np.arange(4.0, dtype=np.float32))
        tr.close()
        assert tr not in all_tracers()
        tr.close()  # idempotent
        mine = [t for t in report_all()["tracers"]
                if t["tracer"] == "retired"]
        assert len(mine) == 1 and mine[0]["closed"]
        assert mine[0]["sites"]["square"]["traces"] == 1
        assert mine[0]["events"] == []

    def test_closed_aggregate_never_evicts(self):
        """An unexpected retrace recorded by an early engine must
        survive ANY number of later tracer retirements — closed
        tracers fold into a cumulative per-name rollup, not a bounded
        list that silently evicts the one fact the report exists to
        keep."""
        import jax.numpy as jnp
        from paddle_tpu.observability.trace import (RecompileTracer,
                                                    report_all)
        early = RecompileTracer(name="agg-victim")
        f = early.jit("hot", lambda x: x + 1)
        f(jnp.zeros((4,)))
        f.jitted.clear_cache()
        f(jnp.zeros((4,)))
        early.close()
        for _ in range(70):   # > the old deque's maxlen of 64
            tr = RecompileTracer(name="agg-churn")
            tr.jit("g", lambda x: x * 2)(jnp.ones(()))
            tr.close()
        rep = report_all()
        victim = [t for t in rep["tracers"]
                  if t["tracer"] == "agg-victim"]
        assert len(victim) == 1 and victim[0]["closed"]
        assert victim[0]["unexpected_retraces"] == 1
        churn = [t for t in rep["tracers"]
                 if t["tracer"] == "agg-churn"]
        assert len(churn) == 1, "same-name closes fold into ONE row"
        assert churn[0]["closed_tracers"] == 70
        assert churn[0]["sites"]["g"]["traces"] == 70
        assert rep["unexpected_retraces"] >= 1

    def test_engine_gc_retires_tracer(self):
        """Engines register tracers STRONGLY (bench reports outlive
        the engine) — so a collected Engine must retire its tracer or
        repeated construction grows the live set forever."""
        import gc
        from paddle_tpu.observability.trace import all_tracers
        net = paddle.nn.Linear(4, 2)
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss())
        tr = model._engine.tracer
        assert tr in all_tracers()
        del model, net
        gc.collect()
        assert tr not in all_tracers()


# -- profiler bridge ------------------------------------------------------

class TestProfilerBridge:
    def test_record_event_lands_in_registry(self):
        import jax.numpy as jnp
        from paddle_tpu.profiler import Profiler, RecordEvent
        reg = MetricsRegistry()
        p = Profiler(registry=reg).start()
        with p.record_event("region_a"):
            float(jnp.ones((4,)).sum())
        with RecordEvent("region_b", p):
            pass
        p.step()
        p.stop()
        for region in ("region_a", "region_b", "train_step"):
            h = reg.get("profiler_region_seconds",
                        {"region": region})
            assert h is not None and h.count == 1, region

    def test_registry_false_disables_bridge(self):
        from paddle_tpu.profiler import Profiler
        p = Profiler(registry=False).start()
        with p.record_event("quiet", sync=False):
            pass
        p.stop()
        assert p.registry is None

    def test_export_chrome_tracing_copies_artifacts(self, tmp_path):
        from paddle_tpu.profiler import export_chrome_tracing

        class FakeProf:
            trace_dir = str(tmp_path / "trace")
        run = tmp_path / "trace" / "plugins" / "profile" / "run1"
        run.mkdir(parents=True)
        (run / "host.trace.json.gz").write_bytes(b"x")
        (run / "host.xplane.pb").write_bytes(b"y")
        (run / "notes.txt").write_bytes(b"ignored")
        out = tmp_path / "export"
        cb = export_chrome_tracing(str(out), worker_name="w0")
        prof = FakeProf()
        cb(prof)
        names = sorted(os.listdir(out))
        assert names == ["w0.host.trace.json.gz", "w0.host.xplane.pb"]
        assert prof._export_dir == str(out)
        assert len(prof._exported) == 2

    def test_export_disambiguates_same_named_runs(self, tmp_path):
        """Two profiling runs under one trace_dir with same-named
        artifacts must BOTH survive the flat export (the colliding
        copy carries its source subpath in the name)."""
        from paddle_tpu.profiler import export_chrome_tracing

        class FakeProf:
            trace_dir = str(tmp_path / "trace")
        for run in ("run1", "run2"):
            d = tmp_path / "trace" / "plugins" / "profile" / run
            d.mkdir(parents=True)
            (d / "host.xplane.pb").write_bytes(run.encode())
        out = tmp_path / "export"
        prof = FakeProf()
        export_chrome_tracing(str(out))(prof)
        assert len(prof._exported) == 2
        payloads = {open(p, "rb").read() for p in prof._exported}
        assert payloads == {b"run1", b"run2"}


# -- bench worker telemetry (subprocess: the real finalize path) ----------

class TestBenchTelemetry:
    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _run(self, code, argv, env_extra, timeout=120):
        import subprocess
        import sys as _sys
        env = dict(os.environ, CAMPAIGN_CHILD="1", **env_extra)
        return subprocess.run([_sys.executable, "-c", code] + argv,
                              cwd=self.REPO, env=env,
                              capture_output=True, text=True,
                              timeout=timeout)

    def test_probe_worker_telemetry_stays_framework_free(self, tmp_path):
        """The probe's time-to-first-signal measures the backend
        handshake — its telemetry must not charge it the full
        paddle_tpu package import (the stdlib-only observability
        modules are file-loaded instead, bench._obs_mod)."""
        code = (
            "import sys; sys.argv = ['bench.py']\n"
            "import bench, json, os\n"
            "bench._TELEMETRY['worker'] = 'probe'\n"
            "bench.worker_probe()\n"
            "bench._finalize_worker_telemetry('probe')\n"
            "assert 'paddle_tpu' not in sys.modules, 'full import paid'\n"
            "d = os.path.join(bench.CAMPAIGN_OUT, 'telemetry', 'probe')\n"
            "doc = json.load(open(os.path.join(d, 'metrics.json')))\n"
            "assert doc['workers'] == ['probe'], doc\n"
            "print('LEAN-OK')\n")
        proc = self._run(code, [], {"JAX_PLATFORMS": "cpu",
                                    "BENCH_CAMPAIGN_DIR": str(tmp_path)})
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "LEAN-OK" in proc.stdout

    def test_metrics_merge_scoped_to_run_id(self, tmp_path):
        """Cross-worker merge spans ONE bench invocation (shared
        BENCH_RUN_ID); a re-invocation with the same telemetry dir
        OVERWRITES — it must not compound the previous run's counters
        or resurrect its retraces."""
        code = (
            "import sys\n"
            "workers = sys.argv[1:]; sys.argv = ['bench.py']\n"
            "import bench\n"
            "for w in workers:\n"
            "    bench._TELEMETRY.clear()\n"
            "    bench._TELEMETRY['worker'] = w\n"
            "    bench._emit('run_note', worker=w)\n"
            "    bench._finalize_worker_telemetry(w)\n")
        env = {"BENCH_TELEMETRY_DIR": str(tmp_path),
               "BENCH_CAMPAIGN_DIR": str(tmp_path)}
        p = self._run(code, ["w1", "w2"],
                      {**env, "BENCH_RUN_ID": "r1"}, timeout=60)
        assert p.returncode == 0, p.stderr[-2000:]
        doc = json.load(open(tmp_path / "metrics.json"))
        assert doc["workers"] == ["w1", "w2"]   # same-run merge
        p = self._run(code, ["w3"],
                      {**env, "BENCH_RUN_ID": "r2"}, timeout=60)
        assert p.returncode == 0, p.stderr[-2000:]
        doc = json.load(open(tmp_path / "metrics.json"))
        assert doc["workers"] == ["w3"]         # re-invocation overwrote
