"""The fusion-audit HLO parser must handle TPU-optimized HLO text.

Regression for the r4 campaign run where the audit reported 0 entry
instructions / empty fusion bodies on the real chip: TPU HLO annotates
layouts inside types (``bf16[8,128]{1,0:T(8,128)(2,1)}``) and inside
the ENTRY/fusion signatures, which the old regexes (that enumerated the
characters a type may contain, and scanned for the first ``{`` after
the computation name) could not survive. CPU HLO carries no layout
annotations, so CPU-only testing never caught it.
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.fusion_audit import parse_entry_computation  # noqa: E402

TPU_STYLE = """HloModule jit_step, is_scheduled=true
%fused_computation.571.clone (param_0.1: bf16[8,1024]{1,0:T(8,128)(2,1)}, param_1.2: bf16[1024]{0:T(1024)}) -> bf16[8,1024]{1,0:T(8,128)(2,1)} {
  %param_0.1 = bf16[8,1024]{1,0:T(8,128)(2,1)} parameter(0)
  %param_1.2 = bf16[1024]{0:T(1024)} parameter(1)
  %broadcast.9 = bf16[8,1024]{1,0:T(8,128)(2,1)} broadcast(bf16[1024]{0:T(1024)} %param_1.2), dimensions={1}
  ROOT %add.5 = bf16[8,1024]{1,0:T(8,128)(2,1)} add(bf16[8,1024]{1,0:T(8,128)(2,1)} %param_0.1, bf16[8,1024]{1,0:T(8,128)(2,1)} %broadcast.9)
}
ENTRY %main.110 (p0: bf16[8,1024]{1,0:T(8,128)(2,1)}, p1: bf16[1024]{0:T(1024)}) -> (bf16[8,1024]{1,0:T(8,128)(2,1)}, f32[]) {
  %p0 = bf16[8,1024]{1,0:T(8,128)(2,1)} parameter(0)
  %p1 = bf16[1024]{0:T(1024)} parameter(1)
  %fusion.2 = bf16[8,1024]{1,0:T(8,128)(2,1)} fusion(bf16[8,1024]{1,0:T(8,128)(2,1)} %p0, bf16[1024]{0:T(1024)} %p1), kind=kLoop, calls=%fused_computation.571.clone
  %dot.3 = bf16[8,1024]{1,0:T(8,128)(2,1)} dot(%fusion.2, %p0), lhs_contracting_dims={1}, rhs_contracting_dims={1}
  %constant.1 = f32[] constant(0)
  ROOT %tuple.9 = (bf16[8,1024]{1,0:T(8,128)(2,1)}, f32[]) tuple(%dot.3, %constant.1)
}
"""

CPU_STYLE = """HloModule jit_f
%fused_computation (param_0.2: f32[1,4]) -> f32[] {
  %param_0.2 = f32[1,4]{1,0} parameter(0)
  ROOT %reduce.1 = f32[] reduce(f32[1,4]{1,0} %param_0.2), dimensions={0,1}, to_apply=%add
}
ENTRY %main.8 (Arg_0.1: f32[1,4]) -> f32[] {
  %Arg_0.1 = f32[1,4]{1,0} parameter(0)
  ROOT %fusion = f32[] fusion(f32[1,4]{1,0} %Arg_0.1), kind=kLoop, calls=%fused_computation
}
"""


def test_tpu_layout_annotated_hlo():
    ops, bodies = parse_entry_computation(TPU_STYLE)
    assert ops == ["parameter", "parameter", "fusion", "dot",
                   "constant", "tuple"]
    body = bodies["fused_computation.571.clone"]
    assert body["add"] == 1 and body["broadcast"] == 1


def test_cpu_plain_hlo():
    ops, bodies = parse_entry_computation(CPU_STYLE)
    assert ops == ["parameter", "fusion"]
    assert bodies["fused_computation"]["reduce"] == 1
