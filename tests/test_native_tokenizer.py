"""Native C++ WordPiece fast path: exact parity with the Python reference
on ASCII/CJK, fallback beyond, and full encode() integration."""
import numpy as np
import pytest

from paddle_tpu.nlp.tokenizer import BertTokenizer, _pttok

VOCAB = (["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
         + list("abcdefghijklmnopqrstuvwxyz")
         + ["##" + c for c in "abcdefghijklmnopqrstuvwxyz"]
         + ["the", "quick", "brown", "fox", "jump", "##s", "##ed", "over",
            "lazy", "dog", "un", "##break", "##able", "!", ",", ".",
            "hello", "world", chr(0x4E2D), chr(0x6587)])


@pytest.fixture(scope="module")
def tok():
    return BertTokenizer({t: i for i, t in enumerate(VOCAB)})


def _ref(tok, text):
    return tok.convert_tokens_to_ids(tok.tokenize(text))


class TestNativeParity:
    def test_lib_loads(self):
        assert _pttok() is not None

    def test_hand_cases(self, tok):
        for text in [
            "The quick brown fox jumps over the lazy dog!",
            "unbreakable, hello world.",
            f"hello {chr(0x4E2D)}{chr(0x6587)} world",
            "zzz qqqqq hello",        # unknown word -> [UNK]
            "", "!!!", "a" * 150,     # > max chars per word -> [UNK]
            "A  B\t\nC",              # whitespace variety
        ]:
            assert tok.text_to_ids(text) == _ref(tok, text), text

    def test_random_ascii_cjk_property(self, tok):
        rng = np.random.default_rng(0)
        alphabet = (list("abcdefghijklmnopqrstuvwxyz ABC !,.")
                    + [chr(0x4E2D), chr(0x6587), chr(0x4E09)])
        for _ in range(60):
            n = int(rng.integers(0, 60))
            text = "".join(rng.choice(alphabet) for _ in range(n))
            assert tok.text_to_ids(text) == _ref(tok, text), repr(text)

    def test_unicode_falls_back_identically(self, tok):
        for text in ["café hello", "naïve fox", "Ω hello", "héllo wörld"]:
            assert tok.text_to_ids(text) == _ref(tok, text), text

    def test_call_uses_fast_path(self, tok):
        out = tok("the quick fox", "hello world", max_length=16,
                  padding=True)
        assert len(out["input_ids"]) == 16
        ids = out["input_ids"]
        cls_id, sep_id = tok.vocab["[CLS]"], tok.vocab["[SEP]"]
        assert ids[0] == cls_id and sep_id in ids

    def test_long_text_buffer_growth(self, tok):
        text = "the quick brown fox " * 500
        assert tok.text_to_ids(text) == _ref(tok, text)

    def test_control_char_whitespace(self, tok):
        # regression: \x1c-\x1f are str.split() whitespace
        for sep in ("\x1c", "\x1d", "\x1e", "\x1f", "\x0b"):
            text = f"hello{sep}world"
            assert tok.text_to_ids(text) == _ref(tok, text), repr(sep)

    def test_newline_in_vocab_token_falls_back(self):
        # regression: a '\n' inside a token mis-aligned the native vocab
        t = BertTokenizer({"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3,
                           "[MASK]": 4, "a\nb": 5, "hello": 6, "world": 7})
        assert t.text_to_ids("hello world") == [6, 7]
        assert getattr(t, "_native_failed", False)  # python path used
