"""LBFGS: convergence on classic problems, strong-Wolfe line search,
closure API parity."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


class TestLBFGS:
    def test_quadratic_converges_fast(self):
        # f(x) = 0.5 x^T A x - b^T x, A spd — newton-like convergence
        rng = np.random.default_rng(0)
        m = rng.standard_normal((6, 6)).astype(np.float32)
        A = m @ m.T + 6 * np.eye(6, dtype=np.float32)
        b = rng.standard_normal(6).astype(np.float32)
        x = paddle.to_tensor(np.zeros(6, np.float32), stop_gradient=False)
        x._retain_grads = True
        At = paddle.to_tensor(A)
        bt = paddle.to_tensor(b)
        opt = paddle.optimizer.LBFGS(learning_rate=1.0, max_iter=30,
                                     line_search_fn="strong_wolfe",
                                     parameters=[x])

        def closure():
            x.clear_grad()
            loss = 0.5 * (x * (At @ x)).sum() - (bt * x).sum()
            loss.backward()
            return loss

        opt.step(closure)
        ref = np.linalg.solve(A, b)
        assert np.allclose(_np(x), ref, atol=1e-3)

    def test_rosenbrock_descends(self):
        xy = paddle.to_tensor(np.array([-1.2, 1.0], np.float32),
                              stop_gradient=False)
        opt = paddle.optimizer.LBFGS(learning_rate=1.0, max_iter=50,
                                     line_search_fn="strong_wolfe",
                                     parameters=[xy])

        def rosen():
            xy.clear_grad()
            x0, x1 = xy[0], xy[1]
            loss = (1 - x0) ** 2 + 100 * (x1 - x0 ** 2) ** 2
            loss.backward()
            return loss

        f0 = float(rosen())
        for _ in range(3):
            opt.step(rosen)
        x0, x1 = _np(xy)
        assert abs(x0 - 1) < 0.05 and abs(x1 - 1) < 0.05
        assert float(rosen()) < f0 * 1e-4

    def test_linear_layer_fit(self):
        # fit y = Wx + b exactly on a small system via the Layer API
        paddle.seed(0)
        import paddle_tpu.nn as nn
        rng = np.random.default_rng(1)
        W_true = rng.standard_normal((3, 2)).astype(np.float32)
        X = rng.standard_normal((20, 3)).astype(np.float32)
        Y = X @ W_true
        fc = nn.Linear(3, 2)
        opt = paddle.optimizer.LBFGS(max_iter=40,
                                     line_search_fn="strong_wolfe",
                                     parameters=fc.parameters())
        xt, yt = paddle.to_tensor(X), paddle.to_tensor(Y)
        lossfn = paddle.nn.MSELoss()

        def closure():
            opt.clear_grad()
            l = lossfn(fc(xt), yt)
            l.backward()
            return l

        opt.step(closure)
        assert float(closure()) < 1e-6

    def test_no_line_search_fixed_step(self):
        x = paddle.to_tensor(np.array([4.0], np.float32),
                             stop_gradient=False)
        opt = paddle.optimizer.LBFGS(learning_rate=0.5, max_iter=20,
                                     parameters=[x])

        def closure():
            x.clear_grad()
            loss = (x ** 2).sum()
            loss.backward()
            return loss

        opt.step(closure)
        assert abs(float(_np(x)[0])) < 1e-3

    def test_weight_decay_applied(self):
        # with wd and zero data-gradient, the minimum shifts toward 0
        # fixed-step mode: with line search, f (closure loss) excludes the
        # decay term the gradient carries — same asymmetry as the reference
        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        opt = paddle.optimizer.LBFGS(learning_rate=0.3, max_iter=40,
                                     weight_decay=1.0,
                                     parameters=[x])

        def closure():
            x.clear_grad()
            loss = ((x - 1.0) ** 2).sum()
            loss.backward()
            return loss

        opt.step(closure)
        # effective objective (x-1)^2 + 0.5*wd*x^2 -> min at 2/3... but
        # LBFGS sees grad 2(x-1) + wd*x = 0 -> x = 2/3
        assert abs(float(_np(x)[0]) - 2.0 / 3.0) < 1e-2

    def test_grad_clip_applied(self):
        from paddle_tpu.nn.clip import ClipGradByGlobalNorm
        x = paddle.to_tensor(np.array([100.0], np.float32),
                             stop_gradient=False)
        opt = paddle.optimizer.LBFGS(learning_rate=1.0, max_iter=1,
                                     grad_clip=ClipGradByGlobalNorm(1.0),
                                     parameters=[x])

        def closure():
            x.clear_grad()
            loss = (x ** 2).sum()
            loss.backward()
            return loss

        opt.step(closure)
        # first direction = -clipped grad (norm 1), scaled by
        # min(1, 1/|g|_1)*lr = 1 -> x moves by at most ~1, not by ~200
        assert abs(float(_np(x)[0]) - 100.0) < 1.5

    def test_requires_closure(self):
        x = paddle.to_tensor(1.0, stop_gradient=False)
        opt = paddle.optimizer.LBFGS(parameters=[x])
        with pytest.raises(ValueError):
            opt.step()

    def test_engine_path_gated(self):
        x = paddle.to_tensor(1.0, stop_gradient=False)
        opt = paddle.optimizer.LBFGS(parameters=[x])
        with pytest.raises(NotImplementedError):
            opt.init_state({"x": x._value})
