"""paddle.flops vs XLA cost_analysis — the analytic-drift check.

`paddle.flops` mirrors the reference's dynamic_flops accounting:
multiply-adds counted ONCE, Linear/Conv layers only (attention score/
value matmuls, norms and activations are ignored). XLA's
`cost_analysis()` counts real FLOPs of the compiled forward (2 per
MAC, everything included). The two must track within a documented
band — if they drift apart, either the analytic mirror or the
introspection capture broke:

    ratio = xla_flops / (2 * paddle.flops MACs)

- lower bound 0.9: XLA must at least account the dense matmuls the
  analytic side counts (a ratio below ~1 means cost analysis lost
  work the convention counts — capture bug);
- upper bound 1.8: the uncounted extras (attention matmuls at small
  seq, BN/ReLU elementwise, layernorm) are bounded for the shapes
  pinned here — a blowout means the analytic mirror stopped seeing a
  layer (hook bug) or XLA started materializing something new.

Skips with a reason where this jax/backend exposes no "flops" key in
cost_analysis (the introspect layer's own null-honesty contract).
CPU-only; shapes are tiny.
"""
import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn.layer import functional_call
from paddle_tpu.observability import introspect
from paddle_tpu.tensor import Tensor

RATIO_LO, RATIO_HI = 0.9, 1.8


def _xla_forward_flops(net, x_np):
    """cost_analysis FLOPs of the jitted eval forward; skips the test
    when this jax build reports no flops key."""
    net.eval()
    params, buffers = net.raw_state()

    def fwd(params, buffers, x):
        out = functional_call(net, params, buffers, Tensor(x))
        return out._value if isinstance(out, Tensor) else out

    compiled = jax.jit(fwd).lower(
        params, buffers, jax.numpy.asarray(x_np)).compile()
    cost = introspect.normalize_cost(compiled.cost_analysis())
    if not cost or not cost.get("flops"):
        pytest.skip(f"jax {jax.__version__} on "
                    f"{jax.default_backend()} exposes no 'flops' key "
                    "in cost_analysis — drift not checkable here")
    return cost["flops"]


def _assert_in_band(xla_flops, analytic_macs, what):
    assert analytic_macs > 0, f"{what}: paddle.flops counted nothing"
    ratio = xla_flops / (2.0 * analytic_macs)
    assert RATIO_LO <= ratio <= RATIO_HI, (
        f"{what}: xla={xla_flops:.3g} vs 2*analytic="
        f"{2 * analytic_macs:.3g} (ratio {ratio:.3f} outside "
        f"[{RATIO_LO}, {RATIO_HI}] — see module docstring)")
    return ratio


def test_gpt_block_analytic_tracks_compiled():
    from paddle_tpu.nlp.gpt import GPTDecoderLayer, _resolve_config
    paddle.seed(0)
    cfg = _resolve_config("gpt-tiny", hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0,
                          use_flash_attention=False)
    blk = GPTDecoderLayer(cfg)
    batch, seq, hidden = 2, 16, cfg.hidden_size
    analytic = paddle.flops(blk, [batch, seq, hidden])
    x = np.random.default_rng(0).standard_normal(
        (batch, seq, hidden)).astype("float32")
    xla = _xla_forward_flops(blk, x)
    _assert_in_band(xla, analytic, "GPT block")


def test_resnet_bottleneck_analytic_tracks_compiled():
    from paddle_tpu.vision.models.resnet import BottleneckBlock
    paddle.seed(0)
    blk = BottleneckBlock(64, 16)   # 64 -> 16 -> 64, no downsample
    batch, hw = 2, 8
    analytic = paddle.flops(blk, [batch, 64, hw, hw])
    x = np.random.default_rng(0).standard_normal(
        (batch, 64, hw, hw)).astype("float32")
    xla = _xla_forward_flops(blk, x)
    _assert_in_band(xla, analytic, "ResNet bottleneck")


def test_bench_analytic_convention_tracks_compiled_train_step():
    """The 6N+12Lhs convention bench.py reports MFU with, against the
    cost analysis of the REAL compiled train step (fwd+bwd+opt) — the
    exact pair whose drift `mfu` vs `mfu_measured` now reports. Wider
    band: the convention ignores the optimizer update and counts
    recompute-free backward."""
    import sys
    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])
    from bench import build_engine, gpt_flops_per_token

    paddle.seed(0)
    batch, seq = 2, 32
    eng = build_engine("gpt-tiny", batch, seq, amp=False,
                       use_flash=False)
    rng = np.random.default_rng(0)
    vocab = eng.network.config.vocab_size
    ids = rng.integers(0, vocab, (batch, seq)).astype("int32")
    labels = rng.integers(0, vocab, (batch, seq)).astype("int32")
    loss, _ = eng.train_batch([ids], [labels])
    float(np.asarray(loss))
    e = introspect.site_cost("train_step", tracer="engine")
    if e is None or not e.get("flops"):
        pytest.skip(f"jax {jax.__version__} exposes no flops for the "
                    "compiled train step")
    analytic = gpt_flops_per_token(eng.network, seq) * batch * seq
    ratio = e["flops"] / analytic
    # 6N already includes the fwd+bwd factor; the loose band covers
    # the embedding/softmax/opt work the convention ignores at tiny
    # hidden sizes
    assert 0.5 <= ratio <= 3.0, (
        f"train-step drift blowout: compiled {e['flops']:.3g} vs "
        f"analytic {analytic:.3g} (ratio {ratio:.3f})")
