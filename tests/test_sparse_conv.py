"""Sparse 3-D convolutions (ref: paddle.sparse.nn Conv3D/SubmConv3D).

Ground truth: a dense conv computed by direct numpy loops over the
zero-filled voxel grid — sparse results must match at every active
output site, and (for SubmConv3D) the active set must not dilate.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import sparse


def _random_points(rng, n, shape_dhw, c, batch=1):
    seen = set()
    coords = []
    while len(coords) < n:
        p = (int(rng.integers(0, batch)),) + tuple(
            int(rng.integers(0, s)) for s in shape_dhw)
        if p not in seen:
            seen.add(p)
            coords.append(p)
    coords = np.asarray(coords, np.int64)
    vals = rng.standard_normal((n, c)).astype(np.float32)
    return coords, vals


def _dense_conv3d(grid, w, stride, padding):
    """Direct-loop NDHWC conv: out[o] = sum_k grid[o*s - p + k] @ w[k]."""
    N, D, H, W, Cin = grid.shape
    kd, kh, kw, _, Cout = w.shape
    sd, sh, sw = stride
    pd, ph, pw = padding
    OD = (D + 2 * pd - kd) // sd + 1
    OH = (H + 2 * ph - kh) // sh + 1
    OW = (W + 2 * pw - kw) // sw + 1
    out = np.zeros((N, OD, OH, OW, Cout), np.float32)
    for n in range(N):
        for od in range(OD):
            for oh in range(OH):
                for ow in range(OW):
                    for dd in range(kd):
                        for hh in range(kh):
                            for ww in range(kw):
                                id_, ih, iw = (od * sd - pd + dd,
                                               oh * sh - ph + hh,
                                               ow * sw - pw + ww)
                                if 0 <= id_ < D and 0 <= ih < H \
                                        and 0 <= iw < W:
                                    out[n, od, oh, ow] += (
                                        grid[n, id_, ih, iw]
                                        @ w[dd, hh, ww])
    return out


def _to_sparse(coords, vals, shape):
    return sparse.sparse_coo_tensor(coords.T, vals, shape)


def test_subm_conv3d_matches_dense_at_active_sites():
    rng = np.random.default_rng(0)
    D = H = W = 5
    coords, vals = _random_points(rng, 12, (D, H, W), c=3)
    x = _to_sparse(coords, vals, (1, D, H, W, 3))
    paddle.seed(1)
    conv = sparse.nn.SubmConv3D(3, 4, 3, padding=1, bias_attr=False)
    out = conv(x)
    # active set identical (submanifold property)
    np.testing.assert_array_equal(
        np.sort(np.asarray(out.indices().numpy()), axis=1),
        np.sort(coords.T, axis=1))
    grid = np.zeros((1, D, H, W, 3), np.float32)
    grid[tuple(coords.T)] = vals
    ref = _dense_conv3d(grid, np.asarray(conv.weight.numpy()),
                        (1, 1, 1), (1, 1, 1))
    out_idx = np.asarray(out.indices().numpy()).T
    out_vals = np.asarray(out.values().numpy())
    for row, v in zip(out_idx, out_vals):
        np.testing.assert_allclose(v, ref[tuple(row)], rtol=1e-4,
                                   atol=1e-5)


def test_conv3d_stride2_matches_dense():
    rng = np.random.default_rng(2)
    D = H = W = 6
    coords, vals = _random_points(rng, 10, (D, H, W), c=2, batch=2)
    x = _to_sparse(coords, vals, (2, D, H, W, 2))
    paddle.seed(3)
    conv = sparse.nn.Conv3D(2, 3, kernel_size=2, stride=2,
                            bias_attr=False)
    out = conv(x)
    grid = np.zeros((2, D, H, W, 2), np.float32)
    grid[tuple(coords.T)] = vals
    ref = _dense_conv3d(grid, np.asarray(conv.weight.numpy()),
                        (2, 2, 2), (0, 0, 0))
    out_idx = np.asarray(out.indices().numpy()).T
    out_vals = np.asarray(out.values().numpy())
    assert len(out_idx)                       # non-empty active set
    for row, v in zip(out_idx, out_vals):
        np.testing.assert_allclose(v, ref[tuple(row)], rtol=1e-4,
                                   atol=1e-5)
    # everywhere off the active set the dense reference is zero
    mask = np.zeros(ref.shape[:4], bool)
    mask[tuple(out_idx.T)] = True
    assert np.abs(ref[~mask]).max() < 1e-6


def test_conv3d_output_shape_and_bias():
    rng = np.random.default_rng(4)
    coords, vals = _random_points(rng, 6, (4, 4, 4), c=2)
    x = _to_sparse(coords, vals, (1, 4, 4, 4, 2))
    paddle.seed(5)
    conv = sparse.nn.Conv3D(2, 5, kernel_size=3, padding=1)
    out = conv(x)
    assert tuple(out.shape) == (1, 4, 4, 4, 5)
    nb = sparse.nn.Conv3D(2, 5, kernel_size=3, padding=1,
                          bias_attr=False)
    nb.weight.set_value(conv.weight)
    diff = (np.asarray(out.values().numpy())
            - np.asarray(nb(x).values().numpy()))
    np.testing.assert_allclose(diff, np.broadcast_to(
        np.asarray(conv.bias.numpy()), diff.shape), rtol=1e-5,
        atol=1e-6)


def test_subm_conv_gradients_chain():
    """Eager backward flows through TWO stacked sparse convs into both
    weights (the tape-linked values chain)."""
    rng = np.random.default_rng(6)
    coords, vals = _random_points(rng, 8, (4, 4, 4), c=2)
    x = _to_sparse(coords, vals, (1, 4, 4, 4, 2))
    paddle.seed(7)
    c1 = sparse.nn.SubmConv3D(2, 3, 3, padding=1, bias_attr=False)
    c2 = sparse.nn.SubmConv3D(3, 2, 3, padding=1, bias_attr=False)
    out = c2(c1(x))
    loss = (out.values() ** 2).sum()
    loss.backward()
    assert c2.weight.grad is not None
    assert np.abs(np.asarray(c2.weight.grad.numpy())).max() > 0
    assert c1.weight.grad is not None
    assert np.abs(np.asarray(c1.weight.grad.numpy())).max() > 0


def test_duplicate_coordinates_rejected():
    vals = np.ones((2, 1), np.float32)
    coords = np.array([[0, 0, 0, 0], [0, 0, 0, 0]])
    x = _to_sparse(coords, vals, (1, 2, 2, 2, 1))
    paddle.seed(12)
    conv = sparse.nn.SubmConv3D(1, 1, 1, bias_attr=False)
    with pytest.raises(ValueError, match="coalesce"):
        conv(x)
    # coalesced input works and sums duplicates
    out = conv(x.coalesce())
    assert out.nnz() == 1
    w = float(np.asarray(conv.weight.numpy()).ravel()[0])
    np.testing.assert_allclose(np.asarray(out.values().numpy()),
                               [[2.0 * w]], rtol=1e-6)


def test_grads_through_to_dense():
    """conv(x).to_dense() backward reaches the weight (the common
    sparse-to-dense head pattern)."""
    rng = np.random.default_rng(13)
    coords, vals = _random_points(rng, 5, (3, 3, 3), c=2)
    x = _to_sparse(coords, vals, (1, 3, 3, 3, 2))
    paddle.seed(14)
    conv = sparse.nn.SubmConv3D(2, 3, 3, padding=1, bias_attr=False)
    dense = conv(x).to_dense()
    (dense ** 2).sum().backward()
    assert conv.weight.grad is not None
    assert np.abs(np.asarray(conv.weight.grad.numpy())).max() > 0


def test_subm_requires_stride_1_and_groups_gate():
    with pytest.raises(ValueError, match="stride 1"):
        sparse.nn.SubmConv3D(2, 2, 3, stride=2)
    with pytest.raises(NotImplementedError, match="groups"):
        sparse.nn.Conv3D(4, 4, 3, groups=2)


def test_gradients_chain_through_sparse_relu():
    """conv -> ReLU -> conv backward must reach the FIRST conv's weight
    (the unary ops carry the tape-linked values chain too)."""
    rng = np.random.default_rng(10)
    coords, vals = _random_points(rng, 6, (4, 4, 4), c=2)
    x = _to_sparse(coords, vals, (1, 4, 4, 4, 2))
    paddle.seed(11)
    c1 = sparse.nn.SubmConv3D(2, 4, 3, padding=1, bias_attr=False)
    c2 = sparse.nn.Conv3D(4, 3, 2, stride=2, bias_attr=False)
    out = c2(sparse.nn.ReLU()(c1(x)))
    (out.values() ** 2).sum().backward()
    assert c1.weight.grad is not None
    assert np.abs(np.asarray(c1.weight.grad.numpy())).max() > 0


def test_sparse_relu_composes_with_conv():
    rng = np.random.default_rng(8)
    coords, vals = _random_points(rng, 6, (3, 3, 3), c=2)
    x = _to_sparse(coords, vals, (1, 3, 3, 3, 2))
    paddle.seed(9)
    conv = sparse.nn.SubmConv3D(2, 2, 3, padding=1)
    y = sparse.nn.ReLU()(conv(x))
    assert np.asarray(y.values().numpy()).min() >= 0
