"""Jit decode fast path == eager cached generate (SURVEY §3.7 decode)."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.nlp import GPTForCausalLM, GPTConfig
from paddle_tpu.nlp.generation import generate, build_decode_fn
from paddle_tpu.tensor import Tensor


def _model():
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        use_flash_attention=False))
    m.eval()
    return m


def test_jit_greedy_matches_eager_generate():
    m = _model()
    ids = Tensor(jnp.asarray([[5, 17, 3, 42], [9, 9, 1, 0]], jnp.int32))
    want = m.generate(ids, max_new_tokens=8, temperature=0.0)
    got = generate(m, ids, max_new_tokens=8, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got._value),
                                  np.asarray(want._value))


def test_jit_decode_single_compile_reuse():
    m = _model()
    fn = build_decode_fn(m, max_new_tokens=4, temperature=0.0)
    params, buffers = m.raw_state()
    ids = jnp.asarray([[1, 2, 3]], jnp.int32)
    out1 = fn(params, buffers, ids, jax.random.PRNGKey(0))
    out2 = fn(params, buffers, jnp.asarray([[4, 5, 6]], jnp.int32),
              jax.random.PRNGKey(1))
    assert out1.shape == out2.shape == (1, 7)


def test_sampled_decode_valid_tokens():
    m = _model()
    out = generate(m, jnp.asarray([[1, 2]], jnp.int32), max_new_tokens=6,
                   temperature=1.0, top_k=5, seed=3)
    arr = np.asarray(out._value)
    assert arr.shape == (1, 8)
    assert (arr >= 0).all() and (arr < 97).all()


def test_static_cache_prefill_matches_full_forward():
    """logits from the cache_index path must equal the plain forward."""
    m = _model()
    ids = Tensor(jnp.asarray([[7, 11, 13, 17, 19]], jnp.int32))
    want = m(ids)  # plain causal forward
    caches = [(Tensor(jnp.zeros((1, 5, 4, 8), jnp.float32)),) * 2
              for _ in range(2)]
    got, _ = m(ids, cache=caches, cache_index=0)
    np.testing.assert_allclose(np.asarray(got._value),
                               np.asarray(want._value),
                               atol=1e-5, rtol=1e-5)


def test_top_p_masks_tail():
    from paddle_tpu.nlp.generation import _mask_top_p
    logits = jnp.asarray([[3.0, 2.0, 1.0, 0.0, -5.0]])
    out = np.asarray(_mask_top_p(logits, 0.6))
    # softmax([3,2,1,0,-5]) ~ [.66,.24,.09,...]: 0.66 >= 0.6 -> only top kept
    assert np.isfinite(out[0, 0])
    assert not np.isfinite(out[0, 2:]).any()
    # top_p=1.0 keeps everything
    full = np.asarray(_mask_top_p(logits, 1.0))
    assert np.isfinite(full).all()


def test_top_p_decode_valid_and_deterministic_seed():
    m = _model()
    ids = Tensor(jnp.asarray([[5, 17, 3, 42]], jnp.int32))
    a = generate(m, ids, max_new_tokens=6, temperature=1.0, top_p=0.9,
                 seed=7)
    b = generate(m, ids, max_new_tokens=6, temperature=1.0, top_p=0.9,
                 seed=7)
    np.testing.assert_array_equal(np.asarray(a._value), np.asarray(b._value))
    assert (np.asarray(a._value) < 97).all()


def test_repetition_penalty_suppresses_repeats():
    from paddle_tpu.nlp.generation import _apply_repetition_penalty
    logits = jnp.asarray([[2.0, -1.0, 0.5]])
    seen = jnp.asarray([[True, True, False]])
    out = np.asarray(_apply_repetition_penalty(logits, seen, 2.0))
    np.testing.assert_allclose(out, [[1.0, -2.0, 0.5]])


def test_eos_early_stop_pads_tail():
    """Once a row emits eos, the remainder of that row is pad."""
    m = _model()
    ids = Tensor(jnp.asarray([[5, 17, 3, 42]], jnp.int32))
    # find what greedy emits, then rerun declaring that token as eos
    base = np.asarray(generate(m, ids, max_new_tokens=6,
                               temperature=0.0)._value)
    eos = int(base[0, 4])  # first generated token
    out = np.asarray(generate(m, ids, max_new_tokens=6, temperature=0.0,
                              eos_token_id=eos, pad_token_id=0)._value)
    assert out[0, 4] == eos
    assert (out[0, 5:] == 0).all()


def test_beam_search_beats_or_equals_greedy_logprob():
    """Beam search's selected sequence must score >= greedy's under the
    model (same start, same length, sum log p) — the defining property."""
    m = _model()
    ids = jnp.asarray([[5, 17, 3, 42]], jnp.int32)
    T = 5

    def seq_logprob(full):
        params, buffers = m.raw_state()
        from paddle_tpu.nn.layer import functional_call
        out = functional_call(m, params, buffers, Tensor(full))
        logits = out[0] if isinstance(out, tuple) else out
        logits = logits._value if hasattr(logits, "_value") else logits
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        tgt = full[:, 1:]
        pick = jnp.take_along_axis(lp, tgt[:, :, None], -1)[:, :, 0]
        return float(pick[:, -T:].sum())

    greedy = generate(m, ids, max_new_tokens=T, temperature=0.0)
    beam = generate(m, ids, max_new_tokens=T, num_beams=4,
                    length_penalty=0.0)
    lp_g = seq_logprob(np.asarray(greedy._value))
    lp_b = seq_logprob(np.asarray(beam._value))
    assert lp_b >= lp_g - 1e-4, (lp_b, lp_g)


def test_beam_search_shapes_and_batch():
    m = _model()
    ids = jnp.asarray([[5, 17, 3], [2, 8, 11]], jnp.int32)
    out = generate(m, ids, max_new_tokens=4, num_beams=3)
    assert np.asarray(out._value).shape == (2, 7)
    assert (np.asarray(out._value)[:, :3] == np.asarray(ids)).all()


def test_model_generate_delegates_advanced_options():
    m = _model()
    ids = Tensor(jnp.asarray([[5, 17, 3]], jnp.int32))
    out = m.generate(ids, max_new_tokens=4, num_beams=3)
    assert np.asarray(out._value).shape == (1, 7)
    out2 = m.generate(ids, max_new_tokens=4, temperature=1.0, top_p=0.8,
                      seed=3)
    assert np.asarray(out2._value).shape == (1, 7)


def test_sampling_strategy_actually_samples():
    """decode_strategy='sampling' with no filters must NOT be argmax
    (review fix: pure temperature sampling was unreachable)."""
    m = _model()
    ids = Tensor(jnp.asarray([[5, 17, 3, 42]], jnp.int32))
    greedy = np.asarray(generate(m, ids, max_new_tokens=8,
                                 temperature=0.0)._value)
    outs = [np.asarray(generate(m, ids, max_new_tokens=8, temperature=1.5,
                                decode_strategy="sampling",
                                seed=s)._value) for s in range(4)]
    assert any(not np.array_equal(o, greedy) for o in outs)
    assert any(not np.array_equal(outs[0], o) for o in outs[1:])


def test_beam_rejects_topk_topp():
    import pytest
    m = _model()
    ids = Tensor(jnp.asarray([[5, 17, 3]], jnp.int32))
    with pytest.raises(ValueError, match="beam_search"):
        generate(m, ids, num_beams=3, top_k=5)


def test_beam_one_equals_greedy():
    m = _model()
    ids = jnp.asarray([[5, 17, 3, 42]], jnp.int32)
    g = np.asarray(generate(m, ids, max_new_tokens=5,
                            temperature=0.0)._value)
    b1 = np.asarray(generate(m, ids, max_new_tokens=5,
                             decode_strategy="beam_search",
                             num_beams=1)._value)
    np.testing.assert_array_equal(g, b1)


def test_bf16_kv_cache_matches_fp32_greedy():
    """cache_dtype='bfloat16' halves decode HBM traffic (the decode
    bottleneck); greedy token ids must match the fp32 cache on a small
    model (logit gaps >> bf16 cache rounding)."""
    paddle.seed(21)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, max_position_embeddings=64,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    ids = jnp.asarray(np.array([[3, 5, 7, 9]], dtype=np.int64))
    a = np.asarray(generate(m, ids, max_new_tokens=8, temperature=0.0))
    b = np.asarray(generate(m, ids, max_new_tokens=8, temperature=0.0,
                            cache_dtype="bfloat16"))
    np.testing.assert_array_equal(a, b)


def test_bf16_kv_cache_beam_path_runs():
    paddle.seed(22)
    cfg = GPTConfig(vocab_size=48, hidden_size=16, num_hidden_layers=1,
                    num_attention_heads=2, max_position_embeddings=48,
                    intermediate_size=32)
    m = GPTForCausalLM(cfg)
    ids = jnp.asarray(np.array([[1, 2, 3]], dtype=np.int64))
    out = generate(m, ids, max_new_tokens=5, num_beams=3,
                   decode_strategy="beam_search", cache_dtype="bfloat16")
    assert np.asarray(out).shape == (1, 8)


def test_generate_memoizes_compiled_decode_fn():
    """Repeat generate() must reuse the compiled program (the axon tunnel
    measured ~30s/call of pure re-compile without this), stay bounded,
    and keep the model collectable."""
    import gc
    import time
    import weakref

    import paddle_tpu.nlp.generation as gen
    from paddle_tpu.nlp.generation import _MEMO_ATTR, clear_decode_cache
    m = _model()
    ids = Tensor(jnp.asarray([[5, 17, 3, 42], [9, 9, 1, 0]], jnp.int32))
    t0 = time.perf_counter()
    first = generate(m, ids, max_new_tokens=4, temperature=0.0)
    t1 = time.perf_counter()
    second = generate(m, ids, max_new_tokens=4, temperature=0.0)
    t2 = time.perf_counter()
    np.testing.assert_array_equal(np.asarray(first._value),
                                  np.asarray(second._value))
    assert (t2 - t1) < (t1 - t0) / 5, "warm call re-traced"
    # numpy/jax scalar args are coerced into hashable key entries
    generate(m, ids, max_new_tokens=np.int64(4), temperature=jnp.float32(0.5),
             top_k=jnp.int32(2), seed=1)
    # distinct arg combos stay bounded by the LRU cap (cap shrunk to
    # keep the test at 5 compiles instead of _MEMO_MAX+3=11)
    monkey_max = 3
    orig_max = gen._MEMO_MAX
    gen._MEMO_MAX = monkey_max
    try:
        for i in range(monkey_max + 2):
            generate(m, ids, max_new_tokens=2, temperature=0.5 + 0.01 * i,
                     top_k=2, seed=i)
        memo = getattr(m, _MEMO_ATTR)
        assert 0 < len(memo) <= monkey_max
    finally:
        gen._MEMO_MAX = orig_max
    clear_decode_cache(m)
    assert len(memo) == 0
    # memo must not leak into checkpoints, nor pin the model in memory
    assert not any("decode_fn_memo" in k for k in m.state_dict())
    ref = weakref.ref(m)
    del m, memo
    gc.collect()
    assert ref() is None, "decode memo kept the model alive"


def test_generate_threadsafe_on_shared_model():
    """Concurrent generate() on one model must not leak tracers or race
    the LRU (functional_call swaps state into the shared model, so the
    whole call is serialized under the module lock)."""
    import threading

    m = _model()
    ids = Tensor(jnp.asarray([[5, 17, 3, 42]], jnp.int32))
    errs = []

    def worker(i):
        try:
            for j in range(3):
                generate(m, ids, max_new_tokens=2,
                         temperature=0.5 + 0.05 * ((i * 3 + j) % 4),
                         top_k=2, seed=j)
        except Exception as e:  # pragma: no cover - failure diagnostics
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs[:1]
