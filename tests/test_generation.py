"""Jit decode fast path == eager cached generate (SURVEY §3.7 decode)."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.nlp import GPTForCausalLM, GPTConfig
from paddle_tpu.nlp.generation import generate, build_decode_fn
from paddle_tpu.tensor import Tensor


def _model():
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        use_flash_attention=False))
    m.eval()
    return m


def test_jit_greedy_matches_eager_generate():
    m = _model()
    ids = Tensor(jnp.asarray([[5, 17, 3, 42], [9, 9, 1, 0]], jnp.int32))
    want = m.generate(ids, max_new_tokens=8, temperature=0.0)
    got = generate(m, ids, max_new_tokens=8, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got._value),
                                  np.asarray(want._value))


def test_jit_decode_single_compile_reuse():
    m = _model()
    fn = build_decode_fn(m, max_new_tokens=4, temperature=0.0)
    params, buffers = m.raw_state()
    ids = jnp.asarray([[1, 2, 3]], jnp.int32)
    out1 = fn(params, buffers, ids, jax.random.PRNGKey(0))
    out2 = fn(params, buffers, jnp.asarray([[4, 5, 6]], jnp.int32),
              jax.random.PRNGKey(1))
    assert out1.shape == out2.shape == (1, 7)


def test_sampled_decode_valid_tokens():
    m = _model()
    out = generate(m, jnp.asarray([[1, 2]], jnp.int32), max_new_tokens=6,
                   temperature=1.0, top_k=5, seed=3)
    arr = np.asarray(out._value)
    assert arr.shape == (1, 8)
    assert (arr >= 0).all() and (arr < 97).all()


def test_static_cache_prefill_matches_full_forward():
    """logits from the cache_index path must equal the plain forward."""
    m = _model()
    ids = Tensor(jnp.asarray([[7, 11, 13, 17, 19]], jnp.int32))
    want = m(ids)  # plain causal forward
    caches = [(Tensor(jnp.zeros((1, 5, 4, 8), jnp.float32)),) * 2
              for _ in range(2)]
    got, _ = m(ids, cache=caches, cache_index=0)
    np.testing.assert_allclose(np.asarray(got._value),
                               np.asarray(want._value),
                               atol=1e-5, rtol=1e-5)
