"""Conv+BN folding (ref: conv_bn_fuse_pass) — numerical equivalence on
the zoo blocks and the Sequential/attribute patterns, plus guards."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.incubate import fuse_conv_bn


def test_sequential_pattern_folds_exactly():
    paddle.seed(0)
    m = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1, bias_attr=False),
        nn.BatchNorm2D(8),
        nn.ReLU(),
        nn.Conv2D(8, 4, 1),
        nn.BatchNorm2D(4),
    )
    # give the BN non-trivial running stats
    m.train()
    x = paddle.to_tensor(np.random.default_rng(1)
                         .standard_normal((4, 3, 16, 16)).astype("f"))
    for _ in range(3):
        m(x)
    m.eval()
    want = np.asarray(m(x)._value)
    m, n = fuse_conv_bn(m)
    assert n == 2
    got = np.asarray(m(x)._value)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)
    # the folded model carries no BatchNorm anymore
    assert not any(type(s).__name__.startswith("BatchNorm")
                   for _, s in m.named_sublayers())


def test_resnet18_folds_exactly():
    from paddle_tpu.vision.models import resnet18
    paddle.seed(3)
    m = resnet18()
    m.eval()
    x = paddle.to_tensor(np.random.default_rng(0)
                         .standard_normal((2, 3, 32, 32)).astype("f"))
    want = np.asarray(m(x)._value)
    m, n = fuse_conv_bn(m)
    assert n == 20  # 17 block convs + stem + 2 downsample convs
    got = np.asarray(m(x)._value)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_training_mode_refuses():
    m = nn.Sequential(nn.Conv2D(3, 4, 3), nn.BatchNorm2D(4))
    m.train()
    with pytest.raises(ValueError, match="eval"):
        fuse_conv_bn(m)
