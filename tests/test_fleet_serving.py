"""Fault-tolerant serving fleet (paddle_tpu/serving_fleet/).

Pins the fleet contracts (docs/robustness.md "Fleet serving"):

- engine lifecycle: explicit serving|draining|closed state, clear
  closed-engine errors, idempotent drain-then-close that never
  wedges, in-flight export;
- crash-mid-decode failover: every request still completes TOKEN-
  EXACT vs a single-replica golden — the completed prefix recovered
  off the carcass is deduped (continuation resubmit), never replayed;
- graceful drain under load: in-flight finishes token-exactly on the
  draining replica, queued work bounces and re-places; rejoin reuses
  the same engine so the whole cycle costs zero recompiles;
- hedging: a slow primary gets a duplicate, the first finisher wins,
  the loser is cancelled, the client sees exactly one result;
- priority load shedding under full-fleet saturation;
- fleet-wide compile counts FROZEN through a crash/drain/rejoin wave
  (zero unexpected retraces — the zero-recompile contract at fleet
  scale).

Everything drills deterministically on CPU via resilience.faults
(replica_crash / replica_wedge / replica_slow / scrape_timeout /
flaky_transport, payload-targeted by replica name). `pytest -m chaos`
selects the chaos classes; the campaign's fleet_chaos_smoke stage
runs exactly that.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
from paddle_tpu.nlp.serving import ServingEngine
from paddle_tpu.resilience import backoff_schedule, faults
from paddle_tpu.resilience.retry import TransientError, \
    call_with_retries
from paddle_tpu.serving_fleet import FleetRouter, InprocReplica

NEW_TOK = 10


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    return m


def _prompts(lens, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype(np.int32) for n in lens]


# prompt lengths straddle pages and pow2 buckets; max_new keeps every
# continuation (prompt + recovered prefix) inside the warmed buckets
WAVE_LENS = (5, 12, 17, 9, 21, 14)


@pytest.fixture(scope="module")
def wave(gpt_model):
    """(prompts, golden) — golden from a fresh single replica."""
    prompts = _prompts(WAVE_LENS)
    eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                        max_seq_len=64, steps_per_dispatch=4)
    refs = eng.generate(prompts, max_new_tokens=NEW_TOK)
    eng.close()
    return prompts, refs


def _engine(model, **kw):
    d = dict(max_slots=2, page_size=16, max_seq_len=64,
             steps_per_dispatch=4)
    d.update(kw)
    return ServingEngine(model, **d)


def _warm(eng):
    """Warm every prefill bucket the wave (and any failover
    continuation: prompt ≤ 21 + delivered ≤ 10 → bucket 32) can land
    in, then reset the measurement window — placement scores read the
    queue-wait p99, and warmup noise would skew the spread."""
    eng.generate(_prompts((5, 17), seed=7), max_new_tokens=4)
    eng.reset_counters()


def _fleet(model, n=3, router_kw=None, **engine_kw):
    engines = [_engine(model, **engine_kw) for _ in range(n)]
    for e in engines:
        _warm(e)
    frozen = [e.compile_counts() for e in engines]
    reps = [InprocReplica(f"r{i}", e) for i, e in enumerate(engines)]
    router = FleetRouter(reps, **(router_kw or {}))
    # register for the session-end metrics.json export the campaign's
    # fleet canary gate diffs (conftest._fleet_stage_metrics_export)
    import conftest
    conftest.fleet_stage_registries.append(router.registry)
    return router, reps, engines, frozen


def _counter(reg, name, **labels):
    c = reg.get(name, labels or None)
    return 0 if c is None else int(c.value)


def _assert_frozen(engines, frozen, router):
    for i, eng in enumerate(engines):
        assert eng.compile_counts() == frozen[i], \
            f"replica {i} compiled something mid-wave"
    assert router.compile_report()["unexpected_retraces"] == 0


# -- engine lifecycle (satellites: state field, drain, closed errors) ----


class TestEngineLifecycle:
    def test_state_field_and_closed_errors(self, gpt_model):
        eng = _engine(gpt_model)
        assert eng.state == "serving"
        assert eng.health()["state"] == "serving"
        eng.drain()
        assert eng.state == "draining"
        assert eng.health()["state"] == "draining"
        with pytest.raises(RuntimeError, match="draining"):
            eng.submit(np.ones(4, np.int32), 4)
        eng.resume()
        assert eng.state == "serving"
        eng.close()
        assert eng.state == "closed"
        assert eng.health()["state"] == "closed"
        with pytest.raises(RuntimeError, match="closed"):
            eng.submit(np.ones(4, np.int32), 4)
        with pytest.raises(RuntimeError, match="closed"):
            eng.step()
        with pytest.raises(RuntimeError, match="closed"):
            eng.drain()
        eng.close()  # idempotent

    def test_draining_completes_inflight_token_exact(self, gpt_model,
                                                     wave):
        """A draining replica stops admitting but finishes in-flight
        work token-exactly; queued requests come back CANCELLED."""
        prompts, refs = wave
        eng = _engine(gpt_model, max_slots=1)
        rids = [eng.submit(p, NEW_TOK) for p in prompts[:3]]
        done = eng.step()          # admits rid0 only (1 slot)
        results = list(done) + eng.drain_to_completion()
        by_id = {r["id"]: r for r in results}
        assert by_id[rids[0]]["status"] == "ok"
        assert by_id[rids[0]]["tokens"] == refs[0], \
            "in-flight request must finish token-exactly under drain"
        for rid in rids[1:]:
            assert by_id[rid]["status"] == "cancelled"
            assert by_id[rid]["tokens"] == []
        assert eng.idle
        eng.close()

    def test_close_releases_everything_never_wedges(self, gpt_model):
        eng = _engine(gpt_model, max_slots=1)
        free0 = eng.free_page_count
        for p in _prompts((5, 9, 12)):
            eng.submit(p, NEW_TOK)
        eng.step()                 # one in flight, two queued
        eng.close()                # impatient close: cancel everything
        assert eng.state == "closed"
        assert eng.free_page_count == free0, "pages must be released"
        eng.close()                # idempotent

    def test_export_inflight(self, gpt_model):
        eng = _engine(gpt_model, max_slots=1)
        rids = [eng.submit(p, NEW_TOK) for p in _prompts((5, 9))]
        eng.step()
        ents = {e["rid"]: e for e in eng.export_inflight()}
        assert set(ents) == set(rids)
        running = ents[rids[0]]
        assert not running["queued"] and len(running["tokens"]) >= 1
        queued = ents[rids[1]]
        assert queued["queued"] and queued["tokens"] == []
        assert queued["max_new_tokens"] == NEW_TOK
        eng.close()


# -- retry jitter (satellite) --------------------------------------------


class TestRetryJitter:
    def test_default_schedule_unchanged(self):
        assert backoff_schedule(3, base_delay=0.05, max_delay=2.0) \
            == [0.05, 0.1, 0.2]

    def test_seeded_jitter_deterministic_and_desynchronized(self):
        a1 = backoff_schedule(4, jitter=0.5, jitter_seed=1)
        a2 = backoff_schedule(4, jitter=0.5, jitter_seed=1)
        b = backoff_schedule(4, jitter=0.5, jitter_seed=2)
        assert a1 == a2, "same seed must replay bit-identically"
        assert a1 != b, "different seeds must de-synchronize"
        base = backoff_schedule(4)
        for d, d0 in zip(a1, base):
            assert d0 <= d <= d0 * 1.5, "jitter stretches, never shrinks"

    def test_call_with_retries_sleeps_the_seeded_schedule(
            self, monkeypatch):
        slept = []
        monkeypatch.setattr(time, "sleep", slept.append)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] <= 2:
                raise TransientError("UNAVAILABLE: injected")
            return "ok"

        assert call_with_retries(flaky, retries=3, base_delay=0.01,
                                 jitter=0.5, jitter_seed=3) == "ok"
        assert slept == backoff_schedule(3, base_delay=0.01, jitter=0.5,
                                         jitter_seed=3)[:2]


# -- fault targeting (fleet fault kinds) ---------------------------------


class TestFaultTargeting:
    def test_payload_pinned_fault_only_fires_for_its_target(self):
        with faults.scenario(("replica_crash", {"replica": "r1"})):
            assert faults.pull("replica_crash", 1,
                               match={"replica": "r0"}) is None
            assert faults.pull("replica_crash", 1,
                               match={"replica": "r1"}) is not None
            assert faults.pull("replica_crash", 2,
                               match={"replica": "r1"}) is None

    def test_unpinned_fault_matches_any_target(self):
        with faults.scenario("replica_slow"):
            assert faults.pull("replica_slow", 1,
                               match={"replica": "anything"}) is not None


# -- chaos suite (campaign stage: fleet_chaos_smoke) ---------------------


@pytest.mark.chaos
class TestFleetChaos:
    def test_crash_mid_decode_failover_token_exact(self, gpt_model,
                                                   wave):
        """THE acceptance drill: a clean 3-replica wave is token-exact
        and actually spreads; then a seeded replica_crash mid-decode —
        every request still completes token-exact (recovered prefix
        deduped), compile counts stay frozen, and the crashed replica
        rejoins without a single new trace."""
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(gpt_model)
        try:
            # clean wave first: parity + health-routed spread
            assert router.generate(prompts, max_new_tokens=NEW_TOK) \
                == refs
            routed = [_counter(router.registry, "fleet_routed_total",
                               replica=f"r{i}") for i in range(3)]
            assert sum(routed) == len(prompts)
            assert sum(1 for n in routed if n) >= 2, routed
            _assert_frozen(engines, frozen, router)
            with faults.scenario(("replica_crash", {"replica": "r1"})):
                outs = router.generate(prompts, max_new_tokens=NEW_TOK)
                fired = faults.fired_log()
            assert outs == refs, "failover must be token-exact"
            assert [k for k, _ in fired] == ["replica_crash"]
            assert reps[1].state == "dead"
            failovers = sum(
                _counter(router.registry, "fleet_failovers_total",
                         replica="r1", reason=reason)
                for reason in ("crash", "wedge"))
            assert failovers >= 1, \
                "the crashed replica held work that was failed over"
            _assert_frozen(engines, frozen, router)
            # no request was lost or duplicated across both waves
            assert _counter(router.registry, "fleet_requests_total",
                            status="ok") == 2 * len(prompts)
            # rejoin the corpse: same engine, zero new traces
            router.rejoin("r1")
            assert router.generate(prompts[:3],
                                   max_new_tokens=NEW_TOK) == refs[:3]
            _assert_frozen(engines, frozen, router)
        finally:
            router.close()

    def test_wedge_failover(self, gpt_model, wave):
        """A wedged (silent, not dead) replica is detected by scrape
        staleness, killed, and its work recovered token-exactly."""
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(
            gpt_model, n=2, router_kw={"wedge_timeout_s": 0.2})
        try:
            with faults.scenario(
                    ("replica_wedge", {"replica": "r0",
                                       "seconds": 30.0})):
                outs = router.generate(prompts, max_new_tokens=NEW_TOK)
            assert outs == refs
            assert reps[0].state == "dead"
            assert sum(_counter(router.registry,
                                "fleet_failovers_total",
                                replica="r0", reason=reason)
                       for reason in ("wedge", "crash")) >= 1
            _assert_frozen(engines, frozen, router)
        finally:
            router.close()

    def test_drain_under_load_and_rejoin(self, gpt_model, wave):
        """Drain a busy replica: its in-flight requests finish token-
        exactly, its queued work bounces and re-places, nothing is
        lost; rejoin costs zero recompiles."""
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(
            gpt_model, n=2, max_slots=1,
            router_kw={"replica_queue_limit": 3})
        try:
            # keep r0 slow so it still has a backlog when the drain
            # lands (deterministic bounce)
            with faults.scenario(
                    ("replica_slow", {"replica": "r0", "count": 1000,
                                      "seconds": 0.02})):
                rids = [router.submit(p, NEW_TOK) for p in prompts]
                deadline = time.monotonic() + 30
                while not any(p.replica == "r0" and p.placed_at
                              for p in router._pending.values()):
                    router.step()
                    assert time.monotonic() < deadline
                    time.sleep(0.002)
                router.drain("r0")
                res = {r["id"]: r for r in router.run_to_completion()}
            assert [res[i]["tokens"] for i in rids] == refs, \
                "drain must lose nothing and stay token-exact"
            deadline = time.monotonic() + 10
            while reps[0].alive and time.monotonic() < deadline:
                time.sleep(0.01)
            assert reps[0].state == "drained"
            _assert_frozen(engines, frozen, router)
            router.rejoin("r0")
            assert router.generate(prompts[:2],
                                   max_new_tokens=NEW_TOK) == refs[:2]
            assert reps[0].state == "serving"
            _assert_frozen(engines, frozen, router)
        finally:
            router.close()

    def test_bounced_work_replaces_onto_rejoined_replica(
            self, gpt_model, wave):
        """A drained fleet-of-one: bounced work can only re-place onto
        the SAME replica after rejoin — the new incarnation must not
        drop the rid as a duplicate delivery (the idempotency ledger
        resets across incarnations)."""
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(
            gpt_model, n=1, max_slots=1,
            router_kw={"replica_queue_limit": 3})
        try:
            deadline = time.monotonic() + 60
            with faults.scenario(
                    ("replica_slow", {"replica": "r0", "count": 1000,
                                      "seconds": 0.02})):
                rids = [router.submit(p, NEW_TOK)
                        for p in prompts[:3]]
                while not any(p.placed_at
                              for p in router._pending.values()):
                    router.step()
                    assert time.monotonic() < deadline
                    time.sleep(0.002)
                router.drain("r0")
                while reps[0].alive:
                    router.step()
                    assert time.monotonic() < deadline
                    time.sleep(0.005)
            assert reps[0].state == "drained"
            router.rejoin("r0")
            res = {x["id"]: x for x in router.run_to_completion()}
            assert [res[i]["tokens"] for i in rids] == refs[:3]
            assert all(res[i]["status"] == "ok" for i in rids)
            _assert_frozen(engines, frozen, router)
        finally:
            router.close()

    def test_hedging_cancels_the_loser(self, gpt_model, wave):
        """A slow primary gets hedged; the hedge wins, the loser is
        cancelled, the client sees exactly one token-exact result."""
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(
            gpt_model, n=2,
            router_kw={"hedge_after_ms": 60, "wedge_timeout_s": 30.0})
        try:
            with faults.scenario(
                    ("replica_slow", {"replica": "r0", "count": 1000,
                                      "seconds": 0.05})):
                router.submit(prompts[0], NEW_TOK)
                (result,) = router.run_to_completion()
            assert result["tokens"] == refs[0]
            assert result["hedged"] and result["replica"] == "r1"
            assert _counter(router.registry, "fleet_hedges_total") == 1
            assert _counter(router.registry, "fleet_hedge_wins_total",
                            by="hedge") == 1
            assert _counter(router.registry, "fleet_requests_total",
                            status="ok") == 1
            _assert_frozen(engines, frozen, router)
        finally:
            router.close()

    def test_shed_by_priority_under_saturation(self, gpt_model, wave):
        """Full-fleet saturation: the global queue overflows and the
        LOWEST-priority requests are shed; every high-priority request
        completes."""
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(
            gpt_model, n=1, max_slots=1,
            router_kw={"max_queue": 2, "replica_queue_limit": 2})
        try:
            prios = [0, 5, 0, 5, 0, 5]
            rids = [router.submit(prompts[i], NEW_TOK, priority=pr)
                    for i, pr in enumerate(prios)]
            res = {r["id"]: r for r in router.run_to_completion()}
            shed = [rid for rid in rids
                    if res[rid]["status"] == "shed"]
            ok = [rid for rid in rids if res[rid]["status"] == "ok"]
            assert len(shed) == 2 and len(ok) == 4
            assert all(prios[rid] == 0 for rid in shed), \
                "only priority-0 work may be shed"
            for rid in ok:
                assert res[rid]["tokens"] == refs[rid]
            assert _counter(router.registry,
                            "fleet_shed_total") == len(shed)
            _assert_frozen(engines, frozen, router)
        finally:
            router.close()

    def test_flaky_transport_and_scrape_timeouts(self, gpt_model,
                                                 wave):
        """Transport blips (lost sends AND lost acks) plus scrape
        timeouts: retries + rid idempotency absorb everything, the
        client sees each result exactly once."""
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(gpt_model, n=2)
        try:
            with faults.scenario(
                    ("flaky_transport", {"replica": "r0", "count": 2}),
                    ("flaky_transport", {"replica": "r0", "count": 2,
                                         "after": 1}),
                    ("scrape_timeout", {"replica": "r1", "count": 2})):
                outs = router.generate(prompts, max_new_tokens=NEW_TOK)
            assert outs == refs
            retries = sum(c.stats.retries
                          for c in router._clients.values())
            assert retries >= 3, "the flaky seam must have fired"
            assert _counter(router.registry,
                            "fleet_scrape_errors_total") >= 1
            assert _counter(router.registry, "fleet_requests_total",
                            status="ok") == len(prompts)
            _assert_frozen(engines, frozen, router)
        finally:
            router.close()

    def test_preemption_drains_the_fleet(self, gpt_model, wave):
        """A process-level preemption notice (the resilience seam)
        drains every replica gracefully; after clear + rejoin the
        fleet serves again with zero new traces."""
        from paddle_tpu.resilience import preemption
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(gpt_model, n=2)
        try:
            assert router.generate(prompts[:2],
                                   max_new_tokens=NEW_TOK) == refs[:2]
            preemption.request()
            deadline = time.monotonic() + 10
            while any(rp.alive for rp in reps) \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert all(rp.state == "drained" for rp in reps)
            preemption.clear()
            for rp in reps:
                router.rejoin(rp.name)
            assert router.generate(prompts[:2],
                                   max_new_tokens=NEW_TOK) == refs[:2]
            _assert_frozen(engines, frozen, router)
        finally:
            preemption.clear()
            router.close()

    def test_router_metrics_endpoint(self, gpt_model, wave):
        """The router is itself a scrape target: /metrics serves the
        fleet registry, /healthz the fleet health snapshot."""
        import json
        from urllib.request import urlopen
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(gpt_model, n=2)
        exp = router.serve_metrics(port=0)
        try:
            assert router.generate(prompts[:3],
                                   max_new_tokens=NEW_TOK) == refs[:3]
            text = urlopen(f"{exp.url}/metrics",
                           timeout=5).read().decode()
            assert "fleet_routed_total" in text
            assert "fleet_placement_wait_seconds_bucket" in text
            health = json.loads(urlopen(f"{exp.url}/healthz",
                                        timeout=5).read().decode())
            assert set(health["replicas"]) == {"r0", "r1"}
            assert health["replicas"]["r0"]["state"] == "serving"
            assert health["compile_report"]["unexpected_retraces"] == 0
        finally:
            router.close()

    def test_idempotent_submit_dedup(self, gpt_model, wave):
        """Double-delivered submit commands (the ack-lost retry case)
        produce exactly one engine request and one result. The result
        plane is at-least-once: the single result is RE-returned by
        every poll until acked (so a crashed router's successor can
        re-harvest it), then retired for good."""
        prompts, refs = wave
        eng = _engine(gpt_model)
        _warm(eng)
        rep = InprocReplica("r0", eng)
        try:
            rep.enqueue(("submit", 0, list(prompts[0]), NEW_TOK,
                         None, 0))
            rep.enqueue(("submit", 0, list(prompts[0]), NEW_TOK,
                         None, 0))  # duplicate delivery
            deadline = time.monotonic() + 30
            got = []
            while len(got) < 1 and time.monotonic() < deadline:
                got.extend(rep.pop_results())
                time.sleep(0.005)
            time.sleep(0.05)
            got.extend(rep.pop_results())
            # ONE distinct engine result, however many times polled
            assert len({r["_rseq"] for r in got}) == 1, got
            assert {r["id"] for r in got} == {0}
            assert got[0]["tokens"] == refs[0]
            # ack retires it; later polls are empty
            rep.ack([got[0]["_rseq"]])
            rep.ack([got[0]["_rseq"]])  # idempotent
            assert rep.pop_results() == []
        finally:
            rep.kill()
            eng.close()
