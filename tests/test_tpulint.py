"""tpu-lint suite (ISSUE 13) — per-rule positive/negative fixtures,
suppression honoring, baseline stability under line drift, the
campaign gate in both directions, and the tier-1 contract itself:
the shipping tree lints clean against the committed baseline.

Pure host-side: tpulint is stdlib-ast only, none of these tests
import jax.
"""
import ast
import json
import os
import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.tpulint import rules as R                      # noqa: E402
from tools.tpulint.core import (Baseline, FileCtx,        # noqa: E402
                                load_baseline, run_lint)

FIXTURES = REPO / "tests" / "fixtures" / "tpulint"


def _ctx(source, relpath="pkg/mod.py"):
    source = textwrap.dedent(source)
    return FileCtx("/x/" + relpath, relpath, source,
                   ast.parse(source))


def _rule(rule_id, source, relpath="pkg/mod.py"):
    return R.RULES[rule_id].check(_ctx(source, relpath))


def _tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def _lint(tmp_path, targets, baseline=None):
    return run_lint(paths=targets, root=str(tmp_path),
                    baseline=baseline or Baseline([]))


# ---------------------------------------------------------------- TRC01

class TestTRC01:
    def test_fires_on_call(self):
        fs = _rule("TRC01", """
            import jax
            f = jax.jit(lambda x: x)
        """)
        assert [f.rule for f in fs] == ["TRC01"]
        assert fs[0].symbol == "jax.jit"

    def test_fires_on_decorator_and_partial(self):
        fs = _rule("TRC01", """
            from functools import partial
            import jax

            @jax.jit
            def f(x):
                return x

            @partial(jax.jit, static_argnums=0)
            def g(n, x):
                return x
        """)
        assert len(fs) == 2

    def test_fires_on_from_import_and_pjit(self):
        fs = _rule("TRC01", """
            from jax import jit
            from jax.experimental.pjit import pjit
            a = jit(lambda x: x)
            b = pjit(lambda x: x)
        """)
        assert len(fs) == 2

    def test_tracer_jit_is_clean(self):
        fs = _rule("TRC01", """
            def build(tracer, fn):
                return tracer.jit("decode", fn, donate_argnums=(0,))
        """)
        assert fs == []

    def test_trace_py_is_exempt(self):
        fs = _rule("TRC01", """
            import jax
            jfn = jax.jit(lambda x: x)
        """, relpath="paddle_tpu/observability/trace.py")
        assert fs == []


# ---------------------------------------------------------------- TRC02

class TestTRC02:
    def test_fires_on_wall_clock_in_jitted_body(self):
        fs = _rule("TRC02", """
            import jax
            import time

            @jax.jit
            def step(x):
                return x + time.time()
        """)
        assert [f.symbol for f in fs] == ["time.time"]

    def test_fires_on_comparison_branch_in_scan_body(self):
        fs = _rule("TRC02", """
            import jax

            def outer(xs):
                def body(carry, x):
                    if carry > 0:
                        return carry, x
                    return carry + x, x
                return jax.lax.scan(body, 0, xs)
        """)
        assert [f.symbol for f in fs] == ["if-on-traced"]

    def test_module_level_scan_body_resolves(self):
        fs = _rule("TRC02", """
            import jax
            import time

            def body(carry, x):
                return carry + time.time(), x

            def run(xs):
                return jax.lax.scan(body, 0.0, xs)
        """)
        assert [f.symbol for f in fs] == ["time.time"]

    def test_truthiness_and_is_none_are_clean(self):
        # `if labels:` / `if eos is not None:` are static pytree
        # structure tests — legal under trace (the engine.py shape)
        fs = _rule("TRC02", """
            import jax

            @jax.jit
            def step(x, labels):
                eos = None
                if labels:
                    x = x + 1
                if eos is not None:
                    x = x + 2
                return x
        """)
        assert fs == []

    def test_static_shape_checks_are_clean(self):
        # `x.ndim == 3`, `len(xs) > 1`, `if not labels:` are
        # trace-time Python ints / pytree-structure tests — the
        # idiomatic static branches every jitted body in the repo
        # uses; flagging them would force suppressions on correct
        # code. A comparison on the traced VALUE itself still fires.
        fs = _rule("TRC02", """
            import jax

            @jax.jit
            def step(x, xs, labels):
                if x.ndim == 3:
                    x = x + 1
                if len(xs) > 1:
                    x = x + 2
                if not labels:
                    x = x + 3
                if x.shape[0] % 2 == 0:
                    x = x + 4
                return x
        """)
        assert fs == []
        fs2 = _rule("TRC02", """
            import jax

            @jax.jit
            def step(x):
                if x > 0:
                    x = x - 1
                return x
        """)
        assert [f.symbol for f in fs2] == ["if-on-traced"]

    def test_nested_traced_body_reported_once(self):
        # a scan body nested INSIDE a jitted body is reachable both
        # via the outer body's recursion and the traced set — one
        # violation must yield exactly one finding, not an inflated
        # non_baselined count and duplicate report rows
        fs = _rule("TRC02", """
            import jax
            import time

            @jax.jit
            def step(x, ts):
                def body(c, t):
                    return c + time.time(), t
                return jax.lax.scan(body, x, ts)
        """)
        assert [f.symbol for f in fs] == ["time.time"]

    def test_untraced_function_is_clean(self):
        fs = _rule("TRC02", """
            import time

            def host_side(x):
                return x + time.time()
        """)
        assert fs == []

    def test_method_name_cannot_alias_scan_body(self):
        # the serving.py regression: a scan body named `step` in one
        # scope must not drag an unrelated `step` METHOD into the
        # traced set
        fs = _rule("TRC02", """
            import jax
            import time

            def build(xs):
                def step(c, x):
                    return c, x
                return jax.lax.scan(step, 0, xs)

            class Engine:
                def step(self):
                    return time.time()
        """)
        assert fs == []


# ---------------------------------------------------------------- DUR01

class TestDUR01:
    def test_fires_in_durable_module(self):
        fs = _rule("DUR01", """
            def save(path, data):
                with open(path, "w") as f:
                    f.write(data)
        """, relpath="paddle_tpu/serving_fleet/journal.py")
        assert len(fs) == 1 and "open" in fs[0].symbol

    def test_fires_on_golden_token_anywhere(self):
        fs = _rule("DUR01", """
            import json
            import os

            def write(GOLDEN, doc, tmp):
                with open(GOLDEN, "w") as f:
                    json.dump(doc, f)
                os.replace(tmp, GOLDEN)
        """, relpath="tools/somesmoke.py")
        assert sorted(f.symbol for f in fs) == ['open(mode="w")',
                                                "os.replace"]

    def test_reads_and_appends_are_clean(self):
        fs = _rule("DUR01", """
            def tail(path):
                with open(path, "rb") as f:
                    return f.read()

            def append(path):
                return open(path, "ab")
        """, relpath="paddle_tpu/serving_fleet/journal.py")
        assert fs == []

    def test_atomic_py_is_exempt(self):
        fs = _rule("DUR01", """
            import os

            def atomic_replace(path, data):
                with open(path + ".tmp", "wb") as f:
                    f.write(data)
                os.replace(path + ".tmp", path)
        """, relpath="paddle_tpu/io/atomic.py")
        assert fs == []

    def test_plain_write_without_token_is_clean(self):
        fs = _rule("DUR01", """
            def note(path, text):
                with open(path, "w") as f:
                    f.write(text)
        """, relpath="tools/scratch.py")
        assert fs == []


# ---------------------------------------------------------------- CON01

_CON01_SRC = """
    import threading

    class Store:
        def __init__(self):
            self._lock = threading.Lock()
            self._data = {{}}
            self._hint = None

        def put(self, k, v):
            with self._lock:
                self._data[k] = v

        def get(self, k):
            {get_body}
"""


class TestCON01:
    def test_fires_on_unlocked_read(self):
        src = _CON01_SRC.format(get_body="return self._data.get(k)")
        fs = _rule("CON01", src,
                   relpath="paddle_tpu/observability/metrics.py")
        assert len(fs) == 1
        assert fs[0].symbol == "self._data"
        assert "Store.get" in fs[0].message

    def test_locked_read_is_clean(self):
        src = _CON01_SRC.format(
            get_body="with self._lock:\n"
                     "                return self._data.get(k)")
        fs = _rule("CON01", src,
                   relpath="paddle_tpu/observability/metrics.py")
        assert fs == []

    def test_foreign_lock_does_not_count_as_held(self):
        # `with global_lock:` (or another object's `_lock`) must not
        # satisfy the OWNING lock by substring accident — this is
        # exactly the torn-scrape race the rule exists to catch
        fs = _rule("CON01", """
            import threading

            global_lock = threading.Lock()

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._data = {}

                def put(self, k, v):
                    with self._lock:
                        self._data[k] = v

                def leak(self, k, v):
                    with global_lock:
                        self._data[k] = v
        """, relpath="paddle_tpu/observability/metrics.py")
        assert [f.symbol for f in fs] == ["self._data"]
        assert "Store.leak" in fs[0].message

    def test_non_container_state_is_not_guarded(self):
        # self._hint (a scalar) is never lock-guarded — CON01 only
        # polices attrs the class itself treats as lock-owned
        src = _CON01_SRC.format(get_body="return self._hint")
        fs = _rule("CON01", src,
                   relpath="paddle_tpu/observability/metrics.py")
        assert fs == []

    def test_out_of_scope_file_is_clean(self):
        src = _CON01_SRC.format(get_body="return self._data.get(k)")
        assert _rule("CON01", src, relpath="pkg/other.py") == []


# ---------------------------------------------------------------- OBS01

class TestOBS01:
    def test_fires_without_allow_nan(self):
        fs = _rule("OBS01", """
            import json

            def export(doc, f):
                json.dump(doc, f)
        """, relpath="paddle_tpu/observability/export2.py")
        assert [f.symbol for f in fs] == ["json.dump"]

    def test_allow_nan_false_is_clean(self):
        fs = _rule("OBS01", """
            import json

            def export(doc, f):
                json.dump(doc, f, allow_nan=False)
        """, relpath="paddle_tpu/serving_fleet/export2.py")
        assert fs == []

    def test_out_of_scope_path_is_clean(self):
        fs = _rule("OBS01", """
            import json

            def export(doc, f):
                json.dump(doc, f)
        """, relpath="tools/whatever.py")
        assert fs == []


# ---------------------------------------------------------------- DOC01

_DOC_CATALOGUE = """
# Observability

`PADDLE_TPU_GHOST_KNOB` is mentioned here only.

## Metric catalogue

| name | type |
|---|---|
| `fleet_good_total` | counter |
| `fleet_j_{a,b}_total` | counter |
| `fleet_ghost_total` | counter |

## Next section
"""

_DOC_CODE = """
import os


def publish(reg):
    reg.counter("fleet_good_total", help="x")
    reg.counter("fleet_undoc_total", help="y")
    for name, h in (("a", "ha"), ("b", "hb")):
        reg.counter(f"fleet_j_{name}_total", help=h)
    return os.environ.get("PADDLE_TPU_UNDOC_KNOB")
"""


class TestDOC01:
    def _run(self, tmp_path, code=_DOC_CODE, doc=_DOC_CATALOGUE):
        _tree(tmp_path, {"docs/observability.md": doc,
                         "pkg/mod.py": code})
        ctxs = [_ctx(code, "pkg/mod.py")]
        return R.RULES["DOC01"].check_project(ctxs, str(tmp_path))

    def test_both_directions_fire(self, tmp_path):
        syms = {f.symbol for f in self._run(tmp_path)}
        assert syms == {"fleet_undoc_total",      # code -> docs
                        "fleet_ghost_total",      # docs -> code
                        "PADDLE_TPU_UNDOC_KNOB",  # code -> docs
                        "PADDLE_TPU_GHOST_KNOB"}  # docs -> code

    def test_fstring_loop_resolution_and_braces(self, tmp_path):
        # fleet_j_{a,b}_total rows are satisfied by the resolved
        # f-string loop emissions — no finding in either direction
        syms = {f.symbol for f in self._run(tmp_path)}
        assert not any(s.startswith("fleet_j_") for s in syms)

    def test_clean_when_reconciled(self, tmp_path):
        doc = _DOC_CATALOGUE.replace(
            "| `fleet_ghost_total` | counter |",
            "| `fleet_undoc_total` | counter |").replace(
            "`PADDLE_TPU_GHOST_KNOB` is mentioned here only.",
            "`PADDLE_TPU_UNDOC_KNOB` is the only knob.")
        assert self._run(tmp_path, doc=doc) == []


# ------------------------------------------------------- driver contracts

class TestSuppressions:
    """Every rule must honor its inline suppression (the acceptance
    bar: one fixture proving it fires is above; one proving the
    suppression works is here)."""

    CASES = {
        "TRC01": ("pkg/mod.py", """
            import jax
            f = jax.jit(lambda x: x)  # tpulint: disable=TRC01
        """),
        "TRC02": ("pkg/mod.py", """
            import jax
            import time

            @jax.jit  # tpulint: disable=TRC01
            def step(x):
                # tpulint: disable-next-line=TRC02
                return x + time.time()
        """),
        "DUR01": ("pkg/mod.py", """
            def write(GOLDEN, doc):
                # tpulint: disable-next-line=DUR01
                with open(GOLDEN, "w") as f:
                    f.write(doc)
        """),
        "CON01": ("paddle_tpu/observability/metrics.py", """
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._data = {}

                def put(self, k, v):
                    with self._lock:
                        self._data[k] = v

                def get(self, k):
                    # tpulint: disable-next-line=CON01
                    return self._data.get(k)
        """),
        "OBS01": ("paddle_tpu/observability/x.py", """
            import json

            def export(doc, f):
                json.dump(doc, f)  # tpulint: disable=OBS01
        """),
        "DOC01": ("pkg/mod.py", """
            import os
            # tpulint: disable-next-line=DOC01
            K = os.environ.get("PADDLE_TPU_SUPPRESSED_KNOB")
        """),
    }

    def test_each_rule_suppressible(self, tmp_path):
        for rule, (rel, src) in self.CASES.items():
            root = tmp_path / rule
            _tree(root, {rel: src})
            rep = _lint(root, [rel.split("/")[0]]
                        if "/" in rel else [rel])
            leaks = [f for f in rep["findings"] if f["rule"] == rule]
            assert leaks == [], (rule, leaks)
            assert rep["suppressed"] >= 1, rule

    def test_suppression_is_rule_scoped(self, tmp_path):
        # disabling OBS01 must not hide an unrelated rule on the line
        _tree(tmp_path, {"pkg/mod.py": """
            import jax
            f = jax.jit(lambda x: x)  # tpulint: disable=OBS01
        """})
        rep = _lint(tmp_path, ["pkg"])
        assert [f["rule"] for f in rep["findings"]] == ["TRC01"]


class TestBaseline:
    VIOLATION = """
        import jax


        def build(fn):
            return jax.jit(fn)
    """

    def _baseline(self):
        return Baseline([{"rule": "TRC01", "path": "pkg/mod.py",
                          "qualname": "build", "symbol": "jax.jit",
                          "justification": "fixture"}])

    def test_matches_on_rule_and_qualname_not_line(self, tmp_path):
        _tree(tmp_path, {"pkg/mod.py": self.VIOLATION})
        rep = _lint(tmp_path, ["pkg"], baseline=self._baseline())
        assert rep["non_baselined"] == 0 and rep["baselined"] == 1

        # drift the finding 6 lines down: the baseline must still hold
        drifted = "# pad\n" * 6 + textwrap.dedent(self.VIOLATION)
        (tmp_path / "pkg" / "mod.py").write_text(drifted)
        rep2 = _lint(tmp_path, ["pkg"], baseline=self._baseline())
        assert rep2["non_baselined"] == 0 and rep2["baselined"] == 1
        assert rep2["findings"][0]["line"] \
            == rep["findings"][0]["line"] + 6

    def test_unused_entries_are_reported(self, tmp_path):
        _tree(tmp_path, {"pkg/mod.py": "x = 1\n"})
        rep = _lint(tmp_path, ["pkg"], baseline=self._baseline())
        assert len(rep["unused_baseline"]) == 1

    def test_syntax_error_is_a_gate_failure(self, tmp_path):
        _tree(tmp_path, {"pkg/mod.py": "def broken(:\n"})
        rep = _lint(tmp_path, ["pkg"])
        assert rep["non_baselined"] == 1
        assert rep["findings"][0]["rule"] == "PARSE"

    def test_missing_target_is_a_gate_failure(self, tmp_path):
        # a typo'd CI path must trip the gate loudly, not scan zero
        # files and read as green (or bury itself under a DOC01 storm)
        _tree(tmp_path, {"pkg/mod.py": "x = 1\n"})
        rep = _lint(tmp_path, ["pgk"])   # typo
        assert rep["non_baselined"] == 1
        f = rep["findings"][0]
        assert (f["rule"], f["symbol"]) == ("PARSE", "missing-target")
        assert "pgk" in f["message"]

    def test_zero_py_target_is_a_gate_failure(self, tmp_path):
        # existing-but-barren targets are the same vacuous-green
        # class: a non-.py file and a dir that lost its sources must
        # both trip, a dir with sources must not
        _tree(tmp_path, {"script": "x = 1\n",
                         "hollow/README.md": "no code here\n",
                         "pkg/mod.py": "x = 1\n"})
        rep = _lint(tmp_path, ["script", "hollow", "pkg"])
        assert rep["files_scanned"] == 1
        assert sorted(f["path"] for f in rep["findings"]) \
            == ["hollow", "script"]
        assert all(f["symbol"] == "missing-target"
                   for f in rep["findings"])


# ------------------------------------------------------------ tier-1 bar

class TestRepoIsClean:
    def test_full_repo_zero_non_baselined(self):
        """THE contract: paddle_tpu/ + tools/ + bench.py lint clean
        against the committed baseline — a new violation fails tier-1
        before it can fail a chaos drill."""
        rep = run_lint(root=str(REPO), baseline=load_baseline())
        fresh = [f for f in rep["findings"] if not f["baselined"]]
        assert fresh == [], "\n".join(
            f"{f['path']}:{f['line']}: {f['rule']} {f['message']}"
            for f in fresh)
        assert rep["files_scanned"] > 150
        assert set(rep["rules_run"]) == {"TRC01", "TRC02", "DUR01",
                                         "CON01", "OBS01", "DOC01",
                                         "MEM01"}

    def test_committed_baseline_has_no_dead_entries(self):
        rep = run_lint(root=str(REPO), baseline=load_baseline())
        assert rep["unused_baseline"] == [], (
            "baseline entries whose findings no longer exist — "
            "delete them, the debt is paid")

    def test_committed_baseline_is_justified(self):
        bl = load_baseline()
        for e in bl.entries:
            j = e.get("justification", "")
            assert j and "UNREVIEWED" not in j, e


# ----------------------------------------------------- the campaign gate

def _cli(args, **kw):
    env = dict(os.environ)
    env.pop("BENCH_TELEMETRY_DIR", None)
    return subprocess.run(
        [sys.executable, "-m", "tools.tpulint", *args],
        cwd=str(REPO), env=env, capture_output=True, text=True,
        timeout=120, **kw)


class TestCampaignGate:
    """The staticcheck stage's gate, proven in BOTH directions from
    the committed fixtures (tests/fixtures/tpulint): the seeded
    violation tree MUST trip (exit 1), its clean twin MUST pass."""

    def test_seeded_violations_trip_the_gate(self, tmp_path):
        p = _cli(["--root", str(FIXTURES), "bad",
                  "--report", str(tmp_path / "lint_report.json")])
        assert p.returncode == 1, p.stdout + p.stderr
        verdict = json.loads(p.stdout.strip().splitlines()[-1])
        assert verdict["ok"] is False
        assert verdict["non_baselined"] >= 4
        report = json.loads((tmp_path / "lint_report.json")
                            .read_text())
        assert {"TRC01", "TRC02", "DUR01", "DOC01"} \
            <= set(report["counts"])

    def test_clean_fixture_passes_the_gate(self, tmp_path):
        p = _cli(["--root", str(FIXTURES), "good",
                  "--report", str(tmp_path / "lint_report.json")])
        assert p.returncode == 0, p.stdout + p.stderr
        verdict = json.loads(p.stdout.strip().splitlines()[-1])
        assert verdict["ok"] is True and verdict["non_baselined"] == 0

    def test_update_baseline_refuses_filtered_run(self, tmp_path):
        # --update-baseline from a --rule/paths-filtered run would
        # rewrite baseline.json from a SLICE of the findings, silently
        # deleting every other rule's entries and their justifications
        for extra in (["--rule", "DUR01"], ["paddle_tpu"]):
            p = _cli([*extra, "--update-baseline",
                      "--baseline", str(tmp_path / "bl.json")])
            assert p.returncode == 2, (extra, p.stdout, p.stderr)
            assert "FULL run" in p.stderr
            assert not (tmp_path / "bl.json").exists()

    def test_update_baseline_refuses_foreign_root(self, tmp_path):
        # --root without an explicit --baseline would rewrite the
        # COMMITTED baseline from a tree where DEFAULT_TARGETS don't
        # even exist (3 missing-target rows over 10 justifications)
        p = _cli(["--root", str(tmp_path), "--update-baseline"])
        assert p.returncode == 2, (p.stdout, p.stderr)
        assert "foreign" in p.stderr

    def test_update_baseline_never_grandfathers_parse(self, tmp_path):
        # a baselined syntax error's key carries no content, so it
        # would match EVERY future syntax error in that file — the
        # gate must stay red until the file parses again
        from tools.tpulint.core import write_baseline, Finding
        fs = [Finding("PARSE", "pkg/mod.py", 1, 0, "<module>",
                      "syntax", "SyntaxError: x"),
              Finding("TRC01", "pkg/mod.py", 3, 0, "f", "jax.jit",
                      "raw jit"),
              Finding("CON01", "pkg/mod.py", 1, 0, "<module>",
                      "checker-error", "checker crashed: Boom")]
        path = tmp_path / "bl.json"
        _, n, skipped = write_baseline(fs, path=str(path))
        assert (n, skipped) == (1, 2)   # the honest CLI verdict
        doc = json.loads(path.read_text())
        assert [e["rule"] for e in doc["entries"]] == ["TRC01"]

    def test_unused_reporting_is_scope_aware(self, tmp_path):
        # a --rule/path-filtered run never sees the other rules' or
        # paths' findings — calling their live entries "unused debt"
        # invites deleting justifications the full gate still needs
        _tree(tmp_path, {"pkg/mod.py": """
            import jax


            def build(fn):
                return jax.jit(fn)
        """, "other/mod.py": "x = 1\n"})
        bl = Baseline([
            {"rule": "TRC01", "path": "pkg/mod.py",
             "qualname": "build", "symbol": "jax.jit",
             "justification": "live"},
            {"rule": "OBS01", "path": "pkg/mod.py",
             "qualname": "emit", "symbol": "json.dumps",
             "justification": "other rule"},
            {"rule": "TRC01", "path": "elsewhere/mod.py",
             "qualname": "f", "symbol": "jax.jit",
             "justification": "other path"}])
        rep = run_lint(paths=["pkg"], rules=["TRC01"],
                       root=str(tmp_path), baseline=bl)
        assert rep["baselined"] == 1
        assert rep["unused_baseline"] == []   # out-of-scope ≠ dead
        # a genuinely dead in-scope entry still reports
        bl2 = Baseline([
            {"rule": "TRC01", "path": "pkg/gone.py",
             "qualname": "f", "symbol": "jax.jit",
             "justification": "dead"}])
        rep2 = run_lint(paths=["pkg"], rules=["TRC01"],
                        root=str(tmp_path), baseline=bl2)
        assert len(rep2["unused_baseline"]) == 1

    def test_validate_stages_gate_both_directions(self, tmp_path,
                                                  monkeypatch):
        """tools/validate_stages.check_lint_report: a completed
        staticcheck stage without a clean lint_report.json must read
        as a preflight problem; a clean one must not."""
        sys.path.insert(0, str(REPO / "tools"))
        import validate_stages as vs
        out = tmp_path / "campaign_out"
        tele = out / "telemetry" / "staticcheck"
        tele.mkdir(parents=True)
        (out / "summary.json").write_text(json.dumps(
            {"staticcheck": {"ok": True, "rc": 0}}))
        monkeypatch.setattr(vs, "OUT", str(out))

        # missing report -> problem
        problems, checked = vs.check_lint_report()
        assert checked == 1 and problems

        # clean report -> no problem
        (tele / "lint_report.json").write_text(
            json.dumps({"non_baselined": 0}))
        problems, checked = vs.check_lint_report()
        assert (problems, checked) == ([], 1)

        # seeded non-baselined count -> MUST trip
        (tele / "lint_report.json").write_text(
            json.dumps({"non_baselined": 2}))
        problems, checked = vs.check_lint_report()
        assert checked == 1 and "non-baselined" in problems[0]
