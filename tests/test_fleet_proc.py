"""Process-isolated replicas + self-healing supervisor
(paddle_tpu/serving_fleet/proc.py, proc_child.py, supervisor.py).

Pins the round-14 contracts (docs/robustness.md "Process
supervision"):

- wire framing: the pipe protocol shares the journal's length-prefix
  + crc32 discipline; the FUZZ ladder truncates / garbles a frame at
  every byte offset and asserts the reader never crashes, never
  duplicates, never misparses — at most the torn frame is lost;
- supervisor state machine: seeded-backoff respawn scheduling
  (deterministic per (seed, name)), the crash-loop breaker ladder
  (trip → quarantine → cooldown → half-open trial), boot-gate
  timeouts — all drilled against stub replicas with injected clocks,
  so the policy logic is testable in milliseconds;
- ServingEngine.warmup(): pre-traced buckets + decode, counted once,
  zero new traces on the first real wave, token-exact parity with an
  unwarmed engine;
- incarnation stamping: a respawned same-name replica's stale-leg
  results are rejected uniformly; journaled placements carry the
  incarnation and recovery treats a bumped incarnation as a fresh
  engine;
- REAL-process chaos (pytest -m chaos; the slow-marked drills run in
  the fleet_supervisor_smoke campaign stage with
  PADDLE_TPU_RUN_SLOW=1): a ServingEngine subprocess SIGKILLed
  mid-decode fails over token-exactly, the supervisor respawns it
  with a warm boot and health-gates it back into rotation under
  frozen compile counts; a persistent exit-at-boot seed trips the
  breaker instead of respawning forever; SIGTERM drains the child
  token-exactly and releases its metrics port.
"""
import json
import os
import signal
import time
import zlib

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
from paddle_tpu.nlp.serving import ServingEngine
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.resilience.retry import backoff_schedule
from paddle_tpu.serving_fleet import (
    FleetRouter, FleetSupervisor, FrameReader, InprocReplica, Journal,
    ProcReplica)
from paddle_tpu.serving_fleet.journal import _frame

NEW_TOK = 10
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SUPPORT = os.path.join(REPO, "tests", "fleet_proc_support.py")


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    return m


def _prompts(lens, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype(np.int32) for n in lens]


WAVE_LENS = (5, 12, 17, 9, 21, 14)


@pytest.fixture(scope="module")
def wave(gpt_model):
    """(prompts, golden) — golden from a fresh single replica; the
    subprocess replicas build the SAME seeded model, so token-exact
    means cross-process token-exact."""
    prompts = _prompts(WAVE_LENS)
    eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                        max_seq_len=64, steps_per_dispatch=4)
    refs = eng.generate(prompts, max_new_tokens=NEW_TOK)
    eng.close()
    return prompts, refs


def _engine(model, **kw):
    d = dict(max_slots=2, page_size=16, max_seq_len=64,
             steps_per_dispatch=4)
    d.update(kw)
    return ServingEngine(model, **d)


def _proc_spec(**kw):
    spec = {"builder": {"path": SUPPORT, "fn": "build_engine"},
            "kwargs": {}, "warmup": [5, 17], "sys_path": [REPO],
            "force_cpu": True, "heartbeat_s": 0.02, "poll_s": 0.002}
    spec.update(kw)
    return spec


def _counter(reg, name, **labels):
    c = reg.get(name, labels or None)
    return 0 if c is None else int(c.value)


def _register_stage_registry(router):
    import conftest
    conftest.fleet_stage_registries.append(router.registry)


# -- wire framing fuzz (satellite) ----------------------------------------


class TestFrameReaderFuzz:
    RECS = [{"t": "hb", "replica": "r0", "queued": 0, "ts": 1.5},
            {"t": "result", "res": {"id": 3, "tokens": [1, 2, 3],
                                    "status": "ok"}},
            {"t": "progress", "rid": 4, "tokens": [9]},
            {"t": "submit", "rid": 5, "prompt": [7] * 40,
             "max_new": 8, "eos": None, "priority": 0},
            {"t": "bye", "state": "drained"}]

    def _stream(self):
        return b"".join(_frame(r) for r in self.RECS)

    def test_truncate_at_every_offset_then_resume(self):
        """A frame cut at ANY byte is held (not dropped) and completes
        when the rest arrives — no loss, no duplicate, no misparse."""
        stream = self._stream()
        for cut in range(len(stream) + 1):
            fr = FrameReader()
            got = fr.feed(stream[:cut]) + fr.feed(stream[cut:])
            assert got == self.RECS, cut
            assert fr.dropped == 0, cut

    def test_kill_mid_write_drops_only_the_torn_frame(self):
        """Feed ONLY a truncated prefix (the SIGKILL-mid-write shape):
        every fully-delivered frame parses, the torn one never
        surfaces as a record, nothing raises."""
        stream = self._stream()
        bounds = []
        off = 0
        for r in self.RECS:
            off += len(_frame(r))
            bounds.append(off)
        for cut in range(len(stream) + 1):
            fr = FrameReader()
            got = fr.feed(stream[:cut])
            n_complete = sum(1 for b in bounds if b <= cut)
            assert got == self.RECS[:n_complete], cut
            assert fr.dropped == 0, cut   # torn tail HELD, not dropped

    def test_garbage_between_frames_resyncs(self):
        """Newline-terminated garbage (a stray library print, a
        corrupted line) is dropped and counted; every real frame
        still parses exactly once."""
        frames = [_frame(r) for r in self.RECS]
        for i in range(len(frames) + 1):
            noise = b"Traceback (most recent call last):\n"
            stream = b"".join(frames[:i]) + noise + b"".join(frames[i:])
            fr = FrameReader()
            got = fr.feed(stream)
            assert got == self.RECS, i
            assert fr.dropped == 1, i

    def test_corrupted_frame_byte_never_misparses(self):
        """Flip one byte inside a frame's payload: the crc rejects the
        line (dropped), every other frame survives."""
        frames = [_frame(r) for r in self.RECS]
        victim = bytearray(frames[2])
        victim[25] ^= 0xFF
        stream = b"".join(frames[:2]) + bytes(victim) \
            + b"".join(frames[3:])
        fr = FrameReader()
        got = fr.feed(stream)
        assert got == self.RECS[:2] + self.RECS[3:]
        assert fr.dropped == 1

    def test_byte_at_a_time_feed(self):
        stream = self._stream()
        fr = FrameReader()
        got = []
        for i in range(len(stream)):
            got.extend(fr.feed(stream[i:i + 1]))
        assert got == self.RECS and fr.dropped == 0


# -- supervisor policy units (stub replicas, injected clock) --------------


class StubReplica:
    """Lifecycle-only replica stand-in: the supervisor's state machine
    is pure policy, testable without engines or processes."""

    def __init__(self, name, fail_incs=(), slow_incs=()):
        self.name = name
        self.incarnation = 1
        self.alive = True
        self.state = "serving"
        self.fail_incs = set(fail_incs)   # incarnations that exit at boot
        self.slow_incs = set(slow_incs)   # incarnations that never hb
        self.rejoins = 0
        self.kills = 0
        self.ops = []

    def die(self):
        self.alive = False
        self.state = "dead"

    def rejoin(self):
        self.rejoins += 1
        self.incarnation += 1
        if self.incarnation in self.fail_incs:
            self.alive = False
            self.state = "dead"
            return
        self.alive = True
        self.state = "booting" if self.incarnation in self.slow_incs \
            else "serving"

    def kill(self, *a, **k):
        self.kills += 1
        self.alive = False
        self.state = "dead"

    def drain(self):
        self.state = "drained"
        self.alive = False

    def scrape(self):
        if self.alive and self.state == "serving":
            return {"replica": self.name, "state": "serving",
                    "warmed": True, "incarnation": self.incarnation,
                    "ts": time.monotonic(), "queued": 0, "running": 0,
                    "free_pages": 8, "queue_wait_p99_s": 0.0}
        return {}

    def enqueue(self, op):
        self.ops.append(tuple(op))

    def pop_results(self):
        return []

    def ack(self, seqs):
        pass

    def export_inflight(self):
        return []

    def compile_counts(self):
        return {}

    def unexpected_retraces(self):
        return 0


class StubRouter:
    def __init__(self, reps):
        self.replicas = {r.name: r for r in reps}
        self.registry = MetricsRegistry()
        self.reinstated = []

    def reinstate(self, name):
        self.reinstated.append(name)

    def step(self):
        return []


class TestSupervisorBreaker:
    def _sup(self, reps, **kw):
        router = StubRouter(reps)
        d = dict(seed=3, breaker_threshold=3, breaker_window_s=60.0,
                 breaker_cooldown_s=100.0, boot_timeout_s=5.0)
        d.update(kw)
        return FleetSupervisor(router, **d), router

    def test_respawn_follows_the_seeded_backoff(self):
        rep = StubReplica("r0")
        sup, router = self._sup([rep])
        t = 1000.0
        rep.die()
        ev = sup.poll(now=t)
        assert ("r0", "down") in ev and ("r0", "respawn_scheduled") in ev
        d1 = sup.backoff_delays("r0", 1)[0]
        # not due yet: nothing happens
        assert sup.poll(now=t + d1 * 0.5) == []
        assert rep.rejoins == 0
        ev = sup.poll(now=t + d1 + 1e-9)
        assert ev == [("r0", "boot_started")] and rep.rejoins == 1
        # healthy heartbeat gates it back in
        ev = sup.poll(now=t + d1 + 0.01)
        assert ev == [("r0", "respawned")]
        assert router.reinstated == ["r0"]
        assert _counter(sup.registry, "fleet_respawns_total",
                        replica="r0") == 1
        assert sup.health()["replicas"]["r0"]["phase"] == "serving"

    def test_crash_loop_trips_quarantines_and_rearms(self):
        rep = StubReplica("rbad", fail_incs=set(range(2, 50)))
        sup, router = self._sup([rep])
        t = 2000.0
        rep.die()
        sup.poll(now=t)                       # down 1 -> backoff
        trips = 0
        for k in range(1, 10):
            if sup.health()["replicas"]["rbad"]["phase"] \
                    == "quarantined":
                break
            delay = sup.backoff_delays("rbad", k)[k - 1]
            t += delay + 1e-6
            sup.poll(now=t)                   # boot attempt (exits)
            ev = sup.poll(now=t)              # exit-at-boot detected
            trips += 1
        h = sup.health()
        assert h["replicas"]["rbad"]["phase"] == "quarantined"
        assert h["quarantined"] == ["rbad"]
        # threshold 3: the initial crash + 2 failed boots
        assert rep.rejoins == 2
        assert _counter(sup.registry, "fleet_crash_loops_total",
                        replica="rbad") == 1
        assert _counter(sup.registry, "fleet_boot_failures_total",
                        replica="rbad", reason="exit_at_boot") == 2
        assert rep.quarantined is True
        assert sup.registry.get("fleet_replicas_quarantined").value == 1
        # quarantine holds: no respawn attempts during the cooldown
        sup.poll(now=t + 50.0)
        assert rep.rejoins == 2
        # cooldown over: half-open trial; a failure re-trips IMMEDIATELY
        ev = sup.poll(now=t + 101.0)
        assert ("rbad", "rearmed") in ev
        sup.poll(now=t + 101.1)               # trial boot (exits)
        ev = sup.poll(now=t + 101.2)
        assert ("rbad", "quarantined") in ev
        assert rep.rejoins == 3
        assert _counter(sup.registry, "fleet_crash_loops_total",
                        replica="rbad") == 2
        # a healthy half-open trial re-arms for good
        rep.fail_incs.clear()
        ev = sup.poll(now=t + 203.0)
        assert ("rbad", "rearmed") in ev
        sup.poll(now=t + 203.1)               # trial boot (healthy)
        ev = sup.poll(now=t + 203.2)
        assert ("rbad", "respawned") in ev
        assert sup.health()["replicas"]["rbad"]["phase"] == "serving"
        assert rep.quarantined is False

    def test_slow_boot_past_the_gate_is_killed_and_counted(self):
        rep = StubReplica("r0", slow_incs={2})
        sup, router = self._sup([rep], boot_timeout_s=5.0)
        t = 3000.0
        rep.die()
        sup.poll(now=t)
        d1 = sup.backoff_delays("r0", 1)[0]
        sup.poll(now=t + d1 + 1e-6)           # boot inc 2 (never hb)
        assert rep.rejoins == 1
        assert sup.poll(now=t + d1 + 4.0) == []   # still inside gate
        ev = sup.poll(now=t + d1 + 5.1)       # past the gate: killed
        assert ("r0", "down") in ev and rep.kills == 1
        assert _counter(sup.registry, "fleet_boot_failures_total",
                        replica="r0", reason="boot_timeout") == 1
        # next attempt (inc 3) boots clean
        d2 = sup.backoff_delays("r0", 2)[1]
        sup.poll(now=t + d1 + 5.1 + d2 + 1e-6)
        ev = sup.poll(now=t + d1 + 5.1 + d2 + 0.01)
        assert ("r0", "respawned") in ev
        boot_h = sup.registry.get("fleet_boot_seconds")
        assert boot_h is not None and boot_h.count >= 1

    def test_drained_replicas_are_left_alone(self):
        rep = StubReplica("r0")
        sup, router = self._sup([rep])
        rep.drain()
        assert sup.poll(now=500.0) == []
        assert rep.rejoins == 0


class TestBackoffDeterminism:
    def test_schedule_is_a_pure_function_of_seed_and_name(self):
        r = StubRouter([StubReplica("r0"), StubReplica("r1")])
        a = FleetSupervisor(r, seed=11)
        b = FleetSupervisor(r, seed=11)
        c = FleetSupervisor(r, seed=12)
        assert a.backoff_delays("r0", 5) == b.backoff_delays("r0", 5), \
            "same (seed, name) must replay bit-identically"
        assert a.backoff_delays("r0", 5) != a.backoff_delays("r1", 5), \
            "different replicas must de-synchronize"
        assert a.backoff_delays("r0", 5) != c.backoff_delays("r0", 5)

    def test_schedule_is_the_documented_retry_ladder(self):
        r = StubRouter([StubReplica("r0")])
        sup = FleetSupervisor(r, seed=7, backoff_base_s=0.1,
                              backoff_max_s=1.0, backoff_jitter=0.5)
        seed = zlib.crc32(b"7:r0") & 0xFFFFFFFF
        assert sup.backoff_delays("r0", 4) == backoff_schedule(
            4, base_delay=0.1, max_delay=1.0, jitter=0.5,
            jitter_seed=seed)
        base = backoff_schedule(4, base_delay=0.1, max_delay=1.0)
        for d, d0 in zip(sup.backoff_delays("r0", 4), base):
            assert d0 <= d <= d0 * 1.5


# -- warmup (satellite) ---------------------------------------------------


class TestWarmup:
    def test_warmed_engine_serves_first_wave_with_zero_new_traces(
            self, gpt_model, wave):
        prompts, refs = wave
        eng = _engine(gpt_model)
        assert not eng.warmed
        warmed = eng.warmup(buckets=(5, 17))
        assert warmed == [16, 32]
        assert eng.warmed and eng.health()["warmed"]
        assert eng.health()["warmed_buckets"] == [16, 32]
        frozen = eng.compile_counts()
        assert frozen == {"prefill_16": 1, "prefill_32": 1,
                          "tail_prefill_16": 1,
                          "tail_prefill_32": 1, "decode": 1}
        # the first REAL wave: token-exact parity with the unwarmed
        # golden AND zero new traces (the TTFT cliff is gone — no
        # compile inside any request's latency)
        assert eng.generate(prompts, max_new_tokens=NEW_TOK) == refs
        assert eng.compile_counts() == frozen, \
            "a warmed engine must not trace on its first wave"
        assert eng.tracer.unexpected_retraces() == 0
        # idempotent: re-warming is free
        assert eng.warmup(buckets=(16, 32)) == []
        assert eng.compile_counts() == frozen
        eng.close()

    def test_warmup_requires_idle_and_open(self, gpt_model):
        eng = _engine(gpt_model)
        eng.submit(np.ones(4, np.int32), 4)
        with pytest.raises(RuntimeError, match="idle"):
            eng.warmup(buckets=(8,))
        eng.run_to_completion()
        eng.close()
        with pytest.raises(RuntimeError, match="closed"):
            eng.warmup(buckets=(8,))


# -- incarnation stamping (satellite) -------------------------------------


class TestIncarnationGuard:
    def test_handle_rejects_stale_incarnation_uniformly(self):
        router = FleetRouter([StubReplica("r0")])
        for status in ("ok", "cancelled", "bounced", "expired"):
            rid = router.submit([1, 2, 3], 4)
            p = router._pending[rid]
            p.replica = "r0"
            p.leg_base["r0"] = 0
            p.leg_inc["r0"] = 2
            router._handle({"id": rid, "tokens": [9], "status": status,
                            "replica": "r0", "incarnation": 1})
            assert not p.done and p.delivered == [], \
                f"stale-incarnation {status} must be dropped"
            router._handle({"id": rid, "tokens": [9, 8],
                            "status": "ok", "replica": "r0",
                            "incarnation": 2})
            assert p.done and router._done[rid]["tokens"] == [9, 8]
            router.results()

    def test_unstamped_results_keep_working(self):
        """Back-compat: a transport that predates the contract (no
        incarnation field) still resolves."""
        router = FleetRouter([StubReplica("r0")])
        rid = router.submit([1, 2], 4)
        p = router._pending[rid]
        p.replica = "r0"
        p.leg_inc["r0"] = 3
        router._handle({"id": rid, "tokens": [5], "status": "ok",
                        "replica": "r0"})
        assert p.done

    def test_inproc_results_stamped_with_accept_incarnation(
            self, gpt_model, wave):
        prompts, refs = wave
        eng = _engine(gpt_model)
        rep = InprocReplica("r0", eng)
        try:
            assert rep.incarnation == 1
            rep.enqueue(("submit", 0, list(prompts[0]), NEW_TOK,
                         None, 0))
            deadline = time.monotonic() + 60
            got = []
            while not got and time.monotonic() < deadline:
                got = rep.pop_results()
                time.sleep(0.005)
            assert got and got[0]["incarnation"] == 1
            rep.ack([r["_rseq"] for r in got])
            rep.kill()
            rep.rejoin()
            assert rep.incarnation == 2
            rep.enqueue(("submit", 1, list(prompts[1]), NEW_TOK,
                         None, 0))
            got = []
            deadline = time.monotonic() + 60
            while not got and time.monotonic() < deadline:
                got = rep.pop_results()
                time.sleep(0.005)
            assert got and got[0]["incarnation"] == 2
            assert got[0]["tokens"] == refs[1]
        finally:
            rep.kill()
            eng.close()

    def test_journal_placed_carries_incarnation(self, tmp_path):
        from paddle_tpu.serving_fleet.journal import reconcile, replay
        j = Journal(os.path.join(tmp_path, "wal"))
        j.append("accepted", rid=0, prompt=[1, 2], max_new=4, eos=None,
                 priority=0, deadline_epoch=None, submitted_epoch=None)
        j.append("placed", rid=0, replica="r0", prefix=0, incarnation=3)
        st = reconcile(replay(j.dir)[0])
        assert st["requests"][0]["placed_incarnation"] == 3
        j.append("failover", rid=0, replica="r0", reason="crash",
                 incarnation=3)
        st = reconcile(replay(j.dir)[0])
        assert st["requests"][0]["placed_incarnation"] is None
        j.close()

    def test_recovery_treats_newer_incarnation_as_fresh_engine(
            self, tmp_path):
        """A rid journaled onto r0@inc1: if r0 has respawned (inc 2)
        by recovery time, the old leg is GONE — the successor must
        re-queue the rid, not trust 'still running there'; with the
        incarnation unchanged, the idempotent continuation-resubmit
        goes back to r0."""
        def build_journal(d):
            j = Journal(d)
            j.append("accepted", rid=0, prompt=[1, 2], max_new=4,
                     eos=None, priority=0, deadline_epoch=None,
                     submitted_epoch=None)
            j.append("placed", rid=0, replica="r0", prefix=0,
                     incarnation=1)
            j.close()

        # same incarnation: continuation-resubmitted to r0
        d1 = os.path.join(tmp_path, "same")
        build_journal(d1)
        rep = StubReplica("r0")
        router = FleetRouter.recover(d1, [rep])
        assert [op[0] for op in rep.ops] == ["submit"]
        assert rep.ops[0][1] == 0
        assert router._pending[0].replica == "r0"
        router.close()

        # bumped incarnation: fresh engine — re-queued, nothing sent
        d2 = os.path.join(tmp_path, "bumped")
        build_journal(d2)
        rep2 = StubReplica("r0")
        rep2.incarnation = 2
        router2 = FleetRouter.recover(d2, [rep2])
        assert rep2.ops == [], \
            "a respawned replica must not be treated as still running"
        assert 0 in router2._queue
        router2.close()


# -- router dynamic membership --------------------------------------------


class TestRouterMembership:
    def test_adopt_and_remove(self):
        r0, r1 = StubReplica("r0"), StubReplica("r1")
        router = FleetRouter([r0])
        router.adopt_replica(r1)
        assert set(router.replicas) == {"r0", "r1"}
        with pytest.raises(ValueError, match="already"):
            router.adopt_replica(StubReplica("r1"))
        with pytest.raises(RuntimeError, match="drain"):
            router.remove_replica("r1")
        r1.drain()
        router.remove_replica("r1")
        assert set(router.replicas) == {"r0"}
        with pytest.raises(KeyError):
            router.reinstate("r1")
        router.close()

    def test_reinstate_clears_lost_without_respawning(self):
        rep = StubReplica("r0")
        router = FleetRouter([rep])
        router._lost.add("r0")
        router._last_scrape["r0"] = {"ts": 0.0}
        router.reinstate("r0")
        assert "r0" not in router._lost
        assert "r0" not in router._last_scrape
        assert rep.rejoins == 0, \
            "reinstate must not respawn (the supervisor already did)"
        router.close()


# -- real-subprocess chaos drills (campaign: fleet_supervisor_smoke) ------


def _wait_for(cond, timeout=180.0, step=None, msg="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        if step is not None:
            step()
        assert time.monotonic() < deadline, f"timed out: {msg}"
        time.sleep(0.01)


def _poll_one(rep, timeout=120.0):
    """Poll the replica's result plane until something lands."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = rep.pop_results()
        if got:
            return got
        time.sleep(0.01)
    raise AssertionError("no result within the deadline")


@pytest.mark.chaos
class TestProcReplicaSmoke:
    def test_boot_serve_sigkill_respawn_token_exact(self, wave,
                                                    tmp_path):
        """Tier-1's one real subprocess drill: boot → warm hello →
        token-exact serve → SIGKILL → death detected → respawn →
        token-exact serve under the fresh incarnation's frozen
        counts."""
        prompts, refs = wave
        rep = ProcReplica("p0", _proc_spec(),
                          flight_dir=str(tmp_path))
        try:
            _wait_for(lambda: rep.state == "serving", 180, msg="boot")
            snap = rep.scrape()
            assert snap["warmed"] and snap["incarnation"] == 1
            frozen = rep.compile_counts()
            assert frozen == {"prefill_16": 1, "prefill_32": 1,
                              "tail_prefill_16": 1,
                              "tail_prefill_32": 1,
                              "decode": 1}, \
                "warm boot must pre-trace exactly the spec'd programs"
            rep.enqueue(("submit", 0, list(prompts[0]), NEW_TOK,
                         None, 0))
            got = _poll_one(rep)
            assert got[0]["tokens"] == refs[0]
            assert got[0]["status"] == "ok"
            assert got[0]["incarnation"] == 1
            rep.ack([r["_rseq"] for r in got])
            # the compile counts FROZE through the wave (the
            # zero-recompile contract, heartbeat-scraped; decode
            # produces max_new - 1 tokens — prefill emits the first)
            _wait_for(lambda: rep.scrape().get("decode_tokens", 0)
                      >= NEW_TOK - 1, 60, msg="hb")
            assert rep.compile_counts() == frozen
            assert rep.unexpected_retraces() == 0
            # the real thing: SIGKILL, no seam
            os.kill(rep.pid, signal.SIGKILL)
            _wait_for(lambda: not rep.alive and rep.state == "dead",
                      60, msg="death detection")
            assert rep.error == "killed" or "exit" in rep.error
            rep.respawn()
            assert rep.incarnation == 2
            _wait_for(lambda: rep.state == "serving", 180,
                      msg="respawn boot")
            assert rep.scrape()["incarnation"] == 2
            rep.enqueue(("submit", 1, list(prompts[1]), NEW_TOK,
                         None, 0))
            got2 = _poll_one(rep)
            assert got2[0]["tokens"] == refs[1]
            assert got2[0]["incarnation"] == 2
            # fresh incarnation, fresh-but-frozen compile budget
            assert rep.compile_counts() == frozen
        finally:
            rep.kill()


@pytest.mark.chaos
@pytest.mark.slow
class TestProcFleetChaos:
    """THE acceptance drills — real processes, real signals. Slow
    (several subprocess boots each): the fleet_supervisor_smoke
    campaign stage runs them with PADDLE_TPU_RUN_SLOW=1."""

    def _fleet(self, tmp_path, n=2, sup_kw=None, **rep_kw):
        reps = [ProcReplica(f"p{i}", _proc_spec(),
                            flight_dir=str(tmp_path), **rep_kw)
                for i in range(n)]
        router = FleetRouter(reps, wedge_timeout_s=60.0)
        d = dict(seed=7, boot_timeout_s=180.0, breaker_threshold=3,
                 breaker_window_s=60.0, breaker_cooldown_s=600.0,
                 backoff_base_s=0.05, backoff_max_s=0.5)
        d.update(sup_kw or {})
        sup = FleetSupervisor(router, **d)
        _register_stage_registry(router)
        return router, sup, reps

    def test_sigkill_mid_decode_failover_respawn_warm_rejoin(
            self, wave, tmp_path):
        from paddle_tpu.observability import flightrec
        prompts, refs = wave
        router, sup, reps = self._fleet(tmp_path)
        victim = reps[1]
        try:
            _wait_for(lambda: all(r.state == "serving" for r in reps),
                      300, msg="fleet boot")
            # wave 1: clean, token-exact, spread across both
            assert router.generate(prompts, max_new_tokens=NEW_TOK) \
                == refs
            routed0 = [_counter(router.registry, "fleet_routed_total",
                                replica=f"p{i}") for i in range(2)]
            assert sum(routed0) == len(prompts)
            assert all(n > 0 for n in routed0), routed0
            # wave 2: SIGKILL p1 once its decode is provably moving
            # (the parent mirror streams partial tokens)
            rids = [router.submit(p, NEW_TOK) for p in prompts]
            _wait_for(lambda: any(e["tokens"] for e in
                                  victim.export_inflight()),
                      120, step=lambda: (router.step(), sup.poll()),
                      msg="victim mid-decode")
            dumps0 = len(flightrec.get_recorder().dumps)
            os.kill(victim.pid, signal.SIGKILL)
            res = {}

            def drain():
                router.step()
                sup.poll()
                for r in router.results():
                    res[r["id"]] = r
                return len(res) == len(rids)

            _wait_for(lambda: drain(), 300, msg="wave 2 completion")
            assert [res[i]["tokens"] for i in rids] == refs, \
                "failover must be token-exact vs the uninterrupted " \
                "golden"
            assert all(res[i]["status"] == "ok" for i in rids)
            assert len(res) == len(rids), "exactly-once by rid"
            assert sum(_counter(router.registry,
                                "fleet_failovers_total",
                                replica="p1", reason=r)
                       for r in ("crash", "wedge")) >= 1
            # the failover left a flight dump
            new_dumps = flightrec.get_recorder().dumps[dumps0:]
            assert any("fleet_failover" in p for p in new_dumps)
            # supervisor: respawn + warm boot + health-gated rejoin
            sup.watch(lambda: victim.state == "serving"
                      and victim.incarnation == 2
                      and sup.health()["replicas"]["p1"]["phase"]
                      == "serving", timeout_s=300)
            assert _counter(router.registry, "fleet_respawns_total",
                            replica="p1") == 1
            snap = victim.scrape()
            assert snap["warmed"] and snap["incarnation"] == 2
            frozen = victim.compile_counts()
            assert frozen == {"prefill_16": 1, "prefill_32": 1,
                              "tail_prefill_16": 1,
                              "tail_prefill_32": 1, "decode": 1}
            # wave 3: the respawned replica takes real traffic with
            # ZERO steady-state recompiles after its warm boot
            rids3 = [router.submit(p, NEW_TOK) for p in prompts]
            res3 = {}

            def drain3():
                router.step()
                sup.poll()
                for r in router.results():
                    res3[r["id"]] = r
                return len(res3) == len(rids3)

            _wait_for(lambda: drain3(), 300, msg="wave 3 completion")
            assert [res3[i]["tokens"] for i in rids3] == refs
            assert _counter(router.registry, "fleet_routed_total",
                            replica="p1") > routed0[1], \
                "the rejoined replica must actually take traffic"
            _wait_for(lambda: victim.scrape().get("round", 0) > 0, 60,
                      msg="fresh hb")
            assert victim.compile_counts() == frozen, \
                "zero steady-state recompiles after warm-boot"
            assert victim.unexpected_retraces() == 0
            assert router.compile_report()["unexpected_retraces"] == 0
        finally:
            router.close()

    def test_persistent_boot_failure_trips_the_breaker(self, wave,
                                                       tmp_path):
        """Exit-at-boot for every respawn (incarnations 2+): the
        breaker must quarantine instead of respawning forever, fleet
        health must degrade honestly, and the healthy replica keeps
        serving."""
        from paddle_tpu.observability import flightrec
        prompts, refs = wave
        reps = [ProcReplica("p0", _proc_spec(),
                            flight_dir=str(tmp_path)),
                ProcReplica("pbad", _proc_spec(),
                            flight_dir=str(tmp_path),
                            child_faults="replica_exit_at_boot@2x99")]
        router = FleetRouter(reps, wedge_timeout_s=60.0)
        sup = FleetSupervisor(router, seed=7, boot_timeout_s=60.0,
                              breaker_threshold=3,
                              breaker_window_s=120.0,
                              breaker_cooldown_s=600.0,
                              backoff_base_s=0.05, backoff_max_s=0.2)
        _register_stage_registry(router)
        try:
            _wait_for(lambda: all(r.state == "serving" for r in reps),
                      300, msg="fleet boot")
            dumps0 = len(flightrec.get_recorder().dumps)
            os.kill(reps[1].pid, signal.SIGKILL)
            sup.watch(lambda: sup.health()["replicas"]["pbad"]["phase"]
                      == "quarantined", timeout_s=300)
            assert _counter(router.registry, "fleet_crash_loops_total",
                            replica="pbad") == 1
            assert _counter(
                sup.registry, "fleet_boot_failures_total",
                replica="pbad", reason="exit_at_boot") >= 2
            assert sup.registry.get(
                "fleet_replicas_quarantined").value == 1
            # honest degradation: quarantine is visible fleet-wide
            assert router.health()["replicas"]["pbad"]["quarantined"]
            assert sup.health()["quarantined"] == ["pbad"]
            new_dumps = flightrec.get_recorder().dumps[dumps0:]
            assert any("fleet_crash_loop" in p for p in new_dumps), \
                "the breaker trip must leave a postmortem"
            # no more respawns while quarantined
            inc = reps[1].incarnation
            for _ in range(20):
                router.step()
                sup.poll()
                time.sleep(0.01)
            assert reps[1].incarnation == inc
            # the healthy half of the fleet still serves, token-exact
            res = {}
            rids = [router.submit(p, NEW_TOK) for p in prompts[:3]]

            def drain():
                router.step()
                sup.poll()
                for r in router.results():
                    res[r["id"]] = r
                return len(res) == len(rids)

            _wait_for(lambda: drain(), 300, msg="degraded wave")
            assert [res[i]["tokens"] for i in rids] == refs[:3]
        finally:
            router.close()

    def test_sigterm_drains_child_token_exact_and_releases_port(
            self, wave, tmp_path):
        """Subprocess hygiene: SIGTERM → the child finishes in-flight
        work token-exactly, emits everything, exits 0 with state
        'drained', and releases its /metrics port; per-incarnation
        artifact dirs keep the carcass's post-mortem safe from the
        next incarnation."""
        from urllib.request import urlopen
        prompts, refs = wave
        # slow_step (an ENGINE seam, armed inside the child) stretches
        # each decode dispatch so the SIGTERM provably lands mid-decode
        rep = ProcReplica(
            "p0", _proc_spec(metrics_port=0, heartbeat_s=0.01),
            flight_dir=str(tmp_path),
            child_faults="slow_step@1x1000:seconds=0.1")
        try:
            _wait_for(lambda: rep.state == "serving", 300, msg="boot")
            _wait_for(lambda: rep.scrape().get("metrics_port"), 60,
                      msg="exporter port on the heartbeat")
            port = rep.scrape()["metrics_port"]
            health = json.loads(urlopen(
                f"http://127.0.0.1:{port}/healthz",
                timeout=5).read().decode())
            assert health["state"] == "serving" and health["warmed"]
            rep.enqueue(("submit", 0, list(prompts[4]), NEW_TOK,
                         None, 0))
            _wait_for(lambda: any(e["tokens"] for e in
                                  rep.export_inflight()), 120,
                      msg="mid-decode")
            os.kill(rep.pid, signal.SIGTERM)
            _wait_for(lambda: rep.state == "drained", 120,
                      msg="drain")
            assert rep._proc.returncode == 0, "a drain is a CLEAN exit"
            got = rep.pop_results()
            assert [r["id"] for r in got] == [0]
            assert got[0]["tokens"] == refs[4], \
                "in-flight work must finish token-exactly under " \
                "SIGTERM"
            # port released on exit
            with pytest.raises(Exception):
                urlopen(f"http://127.0.0.1:{port}/healthz", timeout=2)
            # per-incarnation artifact dir + stderr log exist
            assert os.path.isdir(os.path.join(tmp_path, "p0-inc001"))
            assert os.path.exists(os.path.join(
                tmp_path, "p0-inc001.stderr.log"))
            # a respawn writes NEW per-incarnation paths — the carcass
            # post-mortem is never clobbered
            rep.respawn()
            _wait_for(lambda: rep.state == "serving", 300,
                      msg="respawn")
            assert os.path.isdir(os.path.join(tmp_path, "p0-inc002"))
        finally:
            rep.kill()

    def test_slow_boot_past_the_gate_is_killed_then_recovers(
            self, wave, tmp_path):
        """replica_slow_boot makes incarnation 2 hang pre-import past
        the boot gate: the supervisor kills it, counts a boot_timeout
        failure, and the NEXT attempt (fault exhausted) boots clean
        and rejoins."""
        prompts, refs = wave
        # the injected hang (300s) must dwarf the gate, and the gate
        # (40s) must still tolerate a REAL warm boot on a loaded box
        reps = [ProcReplica("p0", _proc_spec(),
                            flight_dir=str(tmp_path),
                            child_faults="replica_slow_boot@2:"
                                         "seconds=300")]
        router = FleetRouter(reps, wedge_timeout_s=60.0)
        sup = FleetSupervisor(router, seed=5, boot_timeout_s=40.0,
                              breaker_threshold=4,
                              breaker_window_s=300.0,
                              backoff_base_s=0.05, backoff_max_s=0.2)
        _register_stage_registry(router)
        try:
            _wait_for(lambda: reps[0].state == "serving", 300,
                      msg="boot")
            os.kill(reps[0].pid, signal.SIGKILL)
            sup.watch(lambda: _counter(
                sup.registry, "fleet_boot_failures_total",
                replica="p0", reason="boot_timeout") >= 1,
                timeout_s=120)
            sup.watch(lambda: _counter(
                router.registry, "fleet_respawns_total",
                replica="p0") == 1, timeout_s=300)
            assert reps[0].state == "serving"
            assert reps[0].incarnation == 3
            # and the recovered fleet serves token-exact
            res = {}
            rids = [router.submit(p, NEW_TOK) for p in prompts[:2]]

            def drain():
                router.step()
                sup.poll()
                for r in router.results():
                    res[r["id"]] = r
                return len(res) == len(rids)

            _wait_for(lambda: drain(), 300, msg="post-recovery wave")
            assert [res[i]["tokens"] for i in rids] == refs[:2]
        finally:
            router.close()
