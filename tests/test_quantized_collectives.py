"""int8 quantized all-reduce on the virtual 8-device mesh (SURVEY §6
"8-bit-collective option", now implemented — see distributed/quantized.py).
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from paddle_tpu.distributed.quantized import (
    dequantize_int8_blockwise, quantize_int8_blockwise,
    quantized_all_reduce)


def _mesh():
    return Mesh(np.array(jax.devices()), ("dp",))


def test_quantize_roundtrip_exact_on_int_grid():
    x = jnp.asarray(np.random.default_rng(0).integers(
        -127, 128, (4, 512)).astype(np.float32))
    q, s = quantize_int8_blockwise(x, block=256)
    back = dequantize_int8_blockwise(q, s, block=256)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x), atol=1e-4)


def test_quantize_relative_error_bounded():
    x = jnp.asarray(np.random.default_rng(1).standard_normal(
        (8, 1024)).astype(np.float32))
    q, s = quantize_int8_blockwise(x, block=256)
    back = dequantize_int8_blockwise(q, s, block=256)
    err = np.abs(np.asarray(back) - np.asarray(x)).max()
    amax = np.abs(np.asarray(x)).max()
    assert err <= amax / 127.0 + 1e-6


def _qar(mesh, x, block=256):
    fn = shard_map(
        lambda v: quantized_all_reduce(v, "dp", block=block),
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), check_rep=False)
    return fn(x)


def test_quantized_all_reduce_matches_psum():
    mesh = _mesh()
    rng = np.random.default_rng(2)
    # gradient-like magnitudes, one independent slice per device
    x = jnp.asarray(rng.standard_normal((8, 4096)).astype(np.float32) * 1e-2)
    got = np.asarray(_qar(mesh, x))
    want = np.broadcast_to(np.asarray(x).sum(0, keepdims=True), x.shape)
    rel = np.abs(got - want).max() / np.abs(want).max()
    assert rel < 1.5e-2, rel
    # every shard must hold the same reduced value (it IS an all-reduce)
    assert np.allclose(got[0], got[3], atol=1e-6)


def test_quantized_all_reduce_exact_on_small_ints():
    mesh = _mesh()
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(-7, 8, (8, 2048)).astype(np.float32))
    got = np.asarray(_qar(mesh, x))
    want = np.broadcast_to(np.asarray(x).sum(0, keepdims=True), x.shape)
    # per-rank chunks are int-valued and within int8 range; stage-2 sums
    # are <= 8*127 but re-scaled — allow one quantization step
    assert np.abs(got - want).max() <= np.abs(want).max() / 127.0 + 1e-5


def test_quantized_all_reduce_ragged_and_nd():
    """Non-block-multiple sizes are padded internally; ND shapes and
    non-f32 dtypes round-trip."""
    mesh = _mesh()
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((8, 3, 37)).astype(np.float32))
    got = np.asarray(_qar(mesh, x, block=64))
    want = np.broadcast_to(np.asarray(x).sum(0, keepdims=True), x.shape)
    rel = np.abs(got - want).max() / np.abs(want).max()
    assert rel < 2e-2, rel
    xb = x.astype(jnp.bfloat16)
    got_b = _qar(mesh, xb)
    assert got_b.dtype == jnp.bfloat16
