// paddle_tpu native IO runtime.
//
// ref parity: paddle/fluid/operators/reader/buffered_reader.cc (double
// buffered reader), paddle/fluid/memory/allocation/buffered_allocator.cc
// (buffer pool), and the shared-memory DataLoader queue in
// paddle/fluid/dataloader — the reference moves sample batches between
// worker processes and the trainer through C++ queues so Python never
// blocks the pipeline.
//
// TPU-native design: JAX owns device transfer (device_put), so the native
// layer's job is host-side: bounded blocking queues (backpressure without
// the GIL), an aligned reusable buffer pool (stable staging addresses for
// zero-realloc batch assembly), and GIL-free memcpy/gather for collation.
// Python objects never cross this boundary — numpy payloads stay in a
// Python slot table and only slot ids ride the queue (see
// paddle_tpu/io/native.py).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Bounded blocking queue of int64 slot ids.
// ---------------------------------------------------------------------------

struct PtioQueue {
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
  std::deque<int64_t> items;
  size_t capacity;
  bool closed = false;
  std::atomic<int> active{0};  // callers inside push/pop; destroy waits
};

void* ptio_queue_create(int capacity) {
  auto* q = new PtioQueue();
  q->capacity = capacity > 0 ? static_cast<size_t>(capacity) : 1;
  return q;
}

// Blocks while full. Returns 1 on success, 0 if the queue was closed.
int ptio_queue_push(void* hq, long item) {
  auto* q = static_cast<PtioQueue*>(hq);
  q->active.fetch_add(1);
  {
    std::unique_lock<std::mutex> lk(q->mu);
    q->not_full.wait(lk, [q] {
      return q->closed || q->items.size() < q->capacity;
    });
    if (q->closed) {
      q->active.fetch_sub(1);
      return 0;
    }
    q->items.push_back(item);
  }
  q->not_empty.notify_one();
  q->active.fetch_sub(1);
  return 1;
}

// Blocks while empty. Returns the item, or -1 if closed and drained.
long ptio_queue_pop(void* hq) {
  auto* q = static_cast<PtioQueue*>(hq);
  q->active.fetch_add(1);
  int64_t out = -1;
  {
    std::unique_lock<std::mutex> lk(q->mu);
    q->not_empty.wait(lk, [q] { return q->closed || !q->items.empty(); });
    if (!q->items.empty()) {
      out = q->items.front();
      q->items.pop_front();
    }
  }
  q->not_full.notify_one();
  q->active.fetch_sub(1);
  return out;
}

int ptio_queue_size(void* hq) {
  auto* q = static_cast<PtioQueue*>(hq);
  std::lock_guard<std::mutex> lk(q->mu);
  return static_cast<int>(q->items.size());
}

// Wake every blocked producer/consumer; subsequent pushes fail, pops drain
// then return -1.
void ptio_queue_close(void* hq) {
  auto* q = static_cast<PtioQueue*>(hq);
  {
    std::lock_guard<std::mutex> lk(q->mu);
    q->closed = true;
  }
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

// CONTRACT: only call once no other thread can still enter push/pop on
// this handle (the Python bridge closes, joins its producer thread, then
// destroys). The active-counter wait below is a belt-and-braces guard for
// callers already *inside* push/pop at close time; it cannot protect a
// thread that holds the handle but hasn't entered yet.
void ptio_queue_destroy(void* hq) {
  auto* q = static_cast<PtioQueue*>(hq);
  ptio_queue_close(hq);
  while (q->active.load() != 0) {
    std::this_thread::yield();
  }
  delete q;
}

// ---------------------------------------------------------------------------
// Aligned host buffer pool: fixed-size reusable staging buffers so batch
// assembly writes to stable addresses (the pinned-memory analogue; TPU
// DMA from host prefers aligned, long-lived buffers).
// ---------------------------------------------------------------------------

struct PtioPool {
  std::mutex mu;
  std::condition_variable avail;
  std::vector<void*> all;
  std::deque<void*> free_list;
  size_t buf_bytes;
  bool closed = false;
};

void* ptio_pool_create(int n_buffers, size_t bytes) {
  auto* p = new PtioPool();
  p->buf_bytes = bytes;
  for (int i = 0; i < n_buffers; ++i) {
    void* b = nullptr;
    if (posix_memalign(&b, 64, bytes) != 0) {
      b = std::malloc(bytes);
    }
    p->all.push_back(b);
    p->free_list.push_back(b);
  }
  return p;
}

// Blocks until a buffer is free. Returns nullptr if the pool was closed.
void* ptio_pool_acquire(void* hp) {
  auto* p = static_cast<PtioPool*>(hp);
  std::unique_lock<std::mutex> lk(p->mu);
  p->avail.wait(lk, [p] { return p->closed || !p->free_list.empty(); });
  if (p->closed) return nullptr;
  void* b = p->free_list.front();
  p->free_list.pop_front();
  return b;
}

int ptio_pool_release(void* hp, void* buf) {
  auto* p = static_cast<PtioPool*>(hp);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->free_list.push_back(buf);
  }
  p->avail.notify_one();
  return 1;
}

size_t ptio_pool_buffer_bytes(void* hp) {
  return static_cast<PtioPool*>(hp)->buf_bytes;
}

// Wake blocked acquirers; subsequent acquires return nullptr. Frees
// nothing — see ptio_pool_destroy's contract.
void ptio_pool_close(void* hp) {
  auto* p = static_cast<PtioPool*>(hp);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->closed = true;
  }
  p->avail.notify_all();
}

// CONTRACT: only call once no thread is blocked in acquire and no
// acquired buffer is still in use (close first, then join users).
void ptio_pool_destroy(void* hp) {
  auto* p = static_cast<PtioPool*>(hp);
  ptio_pool_close(hp);
  for (void* b : p->all) std::free(b);
  delete p;
}

// ---------------------------------------------------------------------------
// GIL-free copies (ctypes releases the GIL around foreign calls, so these
// overlap with Python-side work — the reference's memcpy-in-C++ reader
// threads get the same effect).
// ---------------------------------------------------------------------------

void ptio_memcpy(void* dst, const void* src, size_t n) {
  std::memcpy(dst, src, n);
}

// Gather n_rows row pointers into one contiguous staging buffer (batch
// collation: list-of-sample-arrays -> [batch, ...] without Python loops).
void ptio_gather_rows(void* dst, const void** srcs, int n_rows,
                      size_t row_bytes) {
  char* out = static_cast<char*>(dst);
  for (int i = 0; i < n_rows; ++i) {
    std::memcpy(out + static_cast<size_t>(i) * row_bytes, srcs[i], row_bytes);
  }
}

}  // extern "C"
