// Native WordPiece encoder (fast path for BertTokenizer).
//
// TPU-native rationale: tokenization is host-side work that competes with
// the input pipeline for the single Python thread; this encoder runs the
// basic-tokenize + greedy-longest-match loop in C++ (GIL released around
// the ctypes call), matching paddlenlp's faster_tokenizer role
// (ref: fast_tokenizer/fast_tokenizer/models/wordpiece.cc).
//
// Scope contract (checked Python-side): input text contains only ASCII or
// CJK codepoints. Anything else (accents needing NFD stripping, unicode
// punctuation/whitespace classes) falls back to the Python reference
// implementation, so parity is exact by construction.
//
// Build: make -C csrc  ->  build/libpttok.so

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Tok {
  std::unordered_map<std::string, int> vocab;
  int unk_id;
  int max_word_chars;
};

inline bool is_ascii_space(uint32_t c) {
  // python str.split() whitespace: \t\n\v\f\r space + \x1c-\x1f
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
         c == 0x0b || (c >= 0x1c && c <= 0x1f);
}

inline bool is_ascii_punct(uint32_t c) {
  return (c >= 33 && c <= 47) || (c >= 58 && c <= 64) ||
         (c >= 91 && c <= 96) || (c >= 123 && c <= 126);
}

inline bool is_cjk(uint32_t c) {
  return (c >= 0x4E00 && c <= 0x9FFF) || (c >= 0x3400 && c <= 0x4DBF) ||
         (c >= 0x20000 && c <= 0x2A6DF) || (c >= 0xF900 && c <= 0xFAFF);
}

// decode one utf-8 codepoint at p (n bytes left); returns byte length, 0 on
// malformed input
inline int decode_utf8(const unsigned char* p, long n, uint32_t* out) {
  if (n <= 0) return 0;
  if (p[0] < 0x80) { *out = p[0]; return 1; }
  if ((p[0] >> 5) == 0x6 && n >= 2) {
    *out = ((p[0] & 0x1F) << 6) | (p[1] & 0x3F);
    return 2;
  }
  if ((p[0] >> 4) == 0xE && n >= 3) {
    *out = ((p[0] & 0x0F) << 12) | ((p[1] & 0x3F) << 6) | (p[2] & 0x3F);
    return 3;
  }
  if ((p[0] >> 3) == 0x1E && n >= 4) {
    *out = ((p[0] & 0x07) << 18) | ((p[1] & 0x3F) << 12) |
           ((p[2] & 0x3F) << 6) | (p[3] & 0x3F);
    return 4;
  }
  return 0;
}

// greedy longest-match wordpiece over a single word; appends ids
void wordpiece(const Tok* t, const std::string& word, int n_chars,
               std::vector<int>* out) {
  if (n_chars > t->max_word_chars) {
    out->push_back(t->unk_id);
    return;
  }
  std::vector<int> pieces;
  size_t start = 0;
  while (start < word.size()) {
    size_t end = word.size();
    int cur = -1;
    size_t cur_end = start;
    while (start < end) {
      std::string sub =
          (start > 0 ? "##" : "") + word.substr(start, end - start);
      auto it = t->vocab.find(sub);
      if (it != t->vocab.end()) {
        cur = it->second;
        cur_end = end;
        break;
      }
      // back off one CODEPOINT (not byte): find previous utf-8 boundary
      do {
        --end;
      } while (end > start && (word[end] & 0xC0) == 0x80);
    }
    if (cur < 0) {
      out->push_back(t->unk_id);
      return;
    }
    pieces.push_back(cur);
    start = cur_end;
  }
  out->insert(out->end(), pieces.begin(), pieces.end());
}

}  // namespace

extern "C" {

// vocab_buf: '\n'-separated tokens; ids: parallel explicit id per line
// (vocab ids need not be contiguous — e.g. dict construction over a token
// list with duplicates leaves holes).
void* pttok_create(const char* vocab_buf, long n_bytes, const int* ids,
                   int n_tokens, int unk_id, int max_word_chars) {
  Tok* t = new Tok();
  t->unk_id = unk_id;
  t->max_word_chars = max_word_chars > 0 ? max_word_chars : 100;
  const char* p = vocab_buf;
  const char* end = vocab_buf + n_bytes;
  int line = 0;
  while (p < end && line < n_tokens) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    size_t len = (nl ? nl : end) - p;
    t->vocab[std::string(p, len)] = ids[line++];
    p += len + 1;
  }
  return t;
}

// Returns #ids written to out, -1 if out_cap too small, -2 if the text is
// outside the fast path's scope (non-ASCII non-CJK codepoint) — caller
// falls back to the Python implementation.
int pttok_encode(void* handle, const char* text, long n_bytes, int do_lower,
                 int* out, int out_cap) {
  const Tok* t = static_cast<const Tok*>(handle);
  const unsigned char* p = reinterpret_cast<const unsigned char*>(text);
  std::vector<int> ids;
  std::string word;
  int word_chars = 0;

  auto flush = [&]() {
    if (!word.empty()) {
      wordpiece(t, word, word_chars, &ids);
      word.clear();
      word_chars = 0;
    }
  };

  long i = 0;
  while (i < n_bytes) {
    uint32_t c;
    int len = decode_utf8(p + i, n_bytes - i, &c);
    if (len == 0) return -2;  // malformed utf-8: punt to Python
    if (c < 128) {
      if (is_ascii_space(c)) {
        flush();
      } else if (is_ascii_punct(c)) {
        flush();
        word.push_back(static_cast<char>(c));
        word_chars = 1;
        flush();
      } else {
        char ch = static_cast<char>(c);
        if (do_lower && ch >= 'A' && ch <= 'Z') ch += 32;
        word.push_back(ch);
        ++word_chars;
      }
    } else if (is_cjk(c)) {
      flush();
      word.assign(text + i, len);
      word_chars = 1;
      flush();
    } else {
      return -2;  // needs NFD/unicode classes: Python path
    }
    i += len;
  }
  flush();

  if (static_cast<int>(ids.size()) > out_cap) return -1;
  memcpy(out, ids.data(), ids.size() * sizeof(int));
  return static_cast<int>(ids.size());
}

void pttok_destroy(void* handle) { delete static_cast<Tok*>(handle); }

}  // extern "C"
