"""Benchmark: flagship GPT pretrain throughput (tokens/sec/chip).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline: measured tokens/s/chip divided by the reference's per-GPU
GPT-1.3B-class baseline share (SURVEY.md §6: ~3.5k tok/s per A100).

Usage: python bench.py [--smoke] [--steps N] [--batch B] [--seq S]
"""
from __future__ import annotations

import argparse
import json
from functools import partial
import sys
import time

if "--smoke" in sys.argv:
    import _cpu_env  # noqa: F401  (axon bypass; must precede jax import)

import jax
import jax.numpy as jnp

BASELINE_TOKENS_PER_SEC_PER_CHIP = 3500.0

# bf16 matmul peak of one v5e chip (the bench target hardware). MFU is
# reported against this regardless of the amp dtype actually used, so an
# fp32 run shows honestly low MFU rather than flattering itself.
TPU_PEAK_FLOPS = 197e12


def log(*a):
    print(*a, file=sys.stderr, flush=True)
    _Watchdog.pet()


class _Watchdog:
    """If the remote TPU backend wedges (observed 2026-07-30: a stalled
    terminal-side compile hangs even jax.devices()), fail fast with a
    diagnostic instead of hanging the driver until its own timeout."""

    _last = time.monotonic()
    LIMIT_S = 900  # 15 min without any progress

    @classmethod
    def pet(cls):
        cls._last = time.monotonic()

    @classmethod
    def start(cls):
        import os
        import threading

        def watch():
            while True:
                time.sleep(30)
                idle = time.monotonic() - cls._last
                if idle > cls.LIMIT_S:
                    print(
                        f"bench watchdog: no progress for {idle:.0f}s — "
                        "TPU backend unresponsive (see BENCHLOG.md "
                        "decode-path incident); aborting",
                        file=sys.stderr, flush=True)
                    os._exit(3)

        threading.Thread(target=watch, daemon=True).start()


def count_params(model):
    import numpy as np
    return int(sum(np.prod(p.shape) for p in model.parameters()))


def gpt_flops_per_token(model, seq):
    """Training FLOPs/token: 6*N for the dense matmuls (fwd+bwd) plus the
    attention score/value matmuls 12*L*h*s (fwd+bwd, causal halving
    ignored to stay comparable with the usual convention)."""
    cfg = model.config
    n = count_params(model)
    return 6 * n + 12 * cfg.num_hidden_layers * cfg.hidden_size * seq


def build_engine(cfg_name, batch, seq, amp, use_flash=True,
                 recompute=False):
    from paddle_tpu.nlp.gpt import (GPTForCausalLM, GPT_CONFIGS,
                                    GPTPretrainingCriterion, _resolve_config)
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.optimizer import AdamW

    max_pos = max(GPT_CONFIGS[cfg_name]["max_position_embeddings"], seq)
    model = GPTForCausalLM(_resolve_config(
        cfg_name, max_position_embeddings=max_pos,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        use_flash_attention=use_flash, recompute=recompute))
    model.train()
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01,
                parameters=model.parameters())
    eng = Engine(model, loss=GPTPretrainingCriterion(), optimizer=opt,
                 amp_dtype=jnp.bfloat16 if amp else None)
    return eng


def run(eng, batch, seq, steps, warmup, scan_steps=0):
    import numpy as np
    rng = np.random.default_rng(0)
    vocab = eng.network.config.vocab_size
    ids = jnp.asarray(rng.integers(0, vocab, (batch, seq)), dtype=jnp.int32)
    labels = jnp.asarray(rng.integers(0, vocab, (batch, seq)),
                         dtype=jnp.int32)
    log("compiling + warmup ...")
    for i in range(warmup):
        t = time.perf_counter()
        loss, _ = eng.train_batch([ids], [labels])
        # float() forces a device->host transfer: the only reliable sync on
        # the axon remote backend, where block_until_ready returns early
        float(loss)
        log(f"  warmup step {i}: {time.perf_counter() - t:.2f}s")
    log(f"warmup done, loss={float(loss):.4f}")
    if scan_steps:
        # amortize the per-dispatch tunnel latency (~6 ms on axon): run K
        # real optimizer steps inside ONE compiled lax.scan per call
        fn = eng._train_fn.__wrapped__ if hasattr(eng._train_fn, "__wrapped__") \
            else eng._train_fn
        key = eng._rng_key
        k = int(scan_steps)

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def multi(params, buffers, opt_state, step0):
            def body(carry, i):
                p, b, s = carry
                p, b, s, l, _ = fn(p, b, s, np.float32(eng._lr_now()),
                                   step0 + i, key, [ids], [labels])
                return (p, b, s), l
            (p, b, s), ls = jax.lax.scan(
                body, (params, buffers, opt_state),
                jnp.arange(k, dtype=jnp.int32))
            return p, b, s, ls[-1]

        params, buffers, opt_state = eng._params, eng._buffers, eng._opt_state
        params, buffers, opt_state, l = multi(params, buffers, opt_state,
                                              np.int32(eng._step))
        float(l)  # compile + warm
        t0 = time.perf_counter()
        calls = max(1, steps // k)
        for c in range(calls):
            params, buffers, opt_state, l = multi(
                params, buffers, opt_state, np.int32(eng._step + (c + 1) * k))
            _Watchdog.pet()
        float(l)
        dt = time.perf_counter() - t0
        # donation deleted the engine's old arrays: rebind so any later
        # train_batch/save on this engine sees live state
        eng._params, eng._buffers, eng._opt_state = params, buffers, opt_state
        eng._step += k * (calls + 1)
        eng.network.load_raw_state(params, buffers)
        return batch * seq * k * calls / dt
    t0 = time.perf_counter()
    for i in range(steps):
        loss, _ = eng.train_batch([ids], [labels])
        _Watchdog.pet()  # dispatch is async: a healthy backend returns fast
    # the param-donation chain makes the last loss depend on every step, so
    # one final sync times the whole window
    float(loss)
    dt = time.perf_counter() - t0
    return batch * seq * steps / dt


BASELINE_RESNET50_IMG_PER_SEC_PER_CHIP = 2900.0  # SURVEY §6: A100 fp16

# ERNIE-3.0-base (118M params): the reference's fleet-class A100 share,
# derived from the GPT-1.3B 3.5k tok/s baseline by the 6N FLOPs/token
# ratio (same training-efficiency assumption): 3.5k * 1.3e9/118e6
BASELINE_ERNIE_TOKENS_PER_SEC_PER_CHIP = 38500.0


def build_ernie_engine(batch, seq, amp):
    import paddle_tpu as paddle
    from paddle_tpu.nlp import (ErnieForPretraining,
                                ErniePretrainingCriterion)
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.optimizer import AdamW

    from paddle_tpu.nlp.ernie import ERNIE_CONFIGS
    from paddle_tpu.nlp.ernie import _resolve_config as _ernie_cfg
    paddle.seed(0)
    max_pos = max(ERNIE_CONFIGS["ernie-3.0-base-zh"]
                  ["max_position_embeddings"], seq)
    model = ErnieForPretraining(_ernie_cfg(
        "ernie-3.0-base-zh", max_position_embeddings=max_pos,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
    model.train()
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01,
                parameters=model.parameters())
    return Engine(model, loss=ErniePretrainingCriterion(), optimizer=opt,
                  amp_dtype=jnp.bfloat16 if amp else None)


def run_ernie(eng, batch, seq, steps, warmup):
    import numpy as np
    rng = np.random.default_rng(0)
    vocab = eng.network.config.vocab_size
    ids = jnp.asarray(rng.integers(0, vocab, (batch, seq)), dtype=jnp.int32)
    # MLM labels: 15% masked positions carry the target id, rest -100
    lbl = np.where(rng.random((batch, seq)) < 0.15,
                   rng.integers(0, vocab, (batch, seq)), -100)
    labels = jnp.asarray(lbl, dtype=jnp.int32)
    nsp = jnp.asarray(rng.integers(0, 2, (batch,)), dtype=jnp.int32)
    log("compiling + warmup (ernie) ...")
    for _ in range(warmup):
        loss, _ = eng.train_batch([ids], [labels, nsp])
        float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, _ = eng.train_batch([ids], [labels, nsp])
        _Watchdog.pet()
    float(loss)
    return batch * seq * steps / (time.perf_counter() - t0)


def build_resnet_engine(amp):
    import paddle_tpu as paddle
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50(num_classes=1000)
    model.train()
    opt = paddle.optimizer.Momentum(0.1, momentum=0.9,
                                    parameters=model.parameters())
    return Engine(model, loss=paddle.nn.CrossEntropyLoss(), optimizer=opt,
                  amp_dtype=jnp.bfloat16 if amp else None)


def run_resnet(eng, batch, steps, warmup, hw=224):
    import numpy as np
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 3, hw, hw)),
                    dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 1000, (batch,)))
    log("compiling + warmup (resnet50) ...")
    for i in range(warmup):
        loss, _ = eng.train_batch([x], [y])
        float(loss)  # real sync (see run())
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, _ = eng.train_batch([x], [y])
        _Watchdog.pet()
    float(loss)
    return batch * steps / (time.perf_counter() - t0)


def main():
    _Watchdog.start()
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--config", default=None)
    ap.add_argument("--model", choices=("gpt", "resnet50", "ernie"),
                    default="gpt")
    ap.add_argument("--no-flash", action="store_true",
                    help="disable the Pallas flash-attention path (fallback "
                         "number if the kernel regresses)")
    ap.add_argument("--recompute", action="store_true",
                    help="rematerialize decoder blocks (enables larger "
                         "batches)")
    ap.add_argument("--scan-steps", type=int, default=0,
                    help="run K optimizer steps per compiled call "
                         "(lax.scan) to amortize dispatch latency")
    ap.add_argument("--decode", action="store_true",
                    help="measure KV-cache generation throughput (flash "
                         "decode) instead of training")
    args = ap.parse_args()

    on_tpu = jax.default_backend() == "tpu"

    if args.decode:
        from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
        from paddle_tpu.nlp.generation import generate
        import numpy as np
        if args.smoke or not on_tpu:
            cfg, batch, new_tok = "gpt-tiny", 2, 16
        else:
            cfg, batch, new_tok = "gpt2-en", 8, 128
        cfg = args.config or cfg
        batch = args.batch or batch
        model = GPTForCausalLM(_resolve_config(
            cfg, max_position_embeddings=1024, hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0,
            use_flash_attention=on_tpu and not args.no_flash))
        model.eval()
        rng = np.random.default_rng(0)
        vocab = model.config.vocab_size
        prompt = jnp.asarray(rng.integers(0, vocab, (batch, 64)), jnp.int32)
        log(f"bench decode: {cfg} batch={batch} new_tokens={new_tok}")
        out = generate(model, prompt, max_new_tokens=new_tok)  # compile
        float(jnp.sum(out._value if hasattr(out, "_value") else out))
        t0 = time.perf_counter()
        reps = 3
        for _ in range(reps):
            out = generate(model, prompt, max_new_tokens=new_tok)
            _Watchdog.pet()
        float(jnp.sum(out._value if hasattr(out, "_value") else out))
        dt = (time.perf_counter() - t0) / reps
        print(json.dumps({
            "metric": "gpt_decode_tokens_per_sec_per_chip",
            "value": round(batch * new_tok / dt, 1),
            "unit": "tokens/s/chip",
            "vs_baseline": None,
            "config": cfg, "batch": batch, "new_tokens": new_tok,
            "ms_per_step": round(dt / new_tok * 1e3, 2),
            "backend": jax.default_backend(),
        }))
        return

    if args.model == "resnet50":
        if args.smoke or not on_tpu:
            batch, steps, warmup, amp, hw = 4, 3, 2, False, 64
        else:
            batch, steps, warmup, amp, hw = 256, 20, 3, True, 224
        batch = args.batch or batch
        steps = args.steps or steps
        log(f"bench: resnet50 batch={batch} hw={hw} steps={steps} "
            f"backend={jax.default_backend()} amp={amp}")
        eng = build_resnet_engine(amp)
        tput = run_resnet(eng, batch, steps, warmup, hw)
        # 4.1 GFLOP fwd inference at 224px, x3 for fwd+bwd; scaled for
        # smaller images
        flops_per_img = 3 * 4.1e9 * (hw / 224.0) ** 2
        print(json.dumps({
            "metric": "resnet50_train_images_per_sec_per_chip",
            "value": round(tput, 1),
            "unit": "images/s/chip",
            # vs_baseline compares against an A100 number — meaningless for
            # a CPU smoke run, so only reported on TPU
            "vs_baseline": round(
                tput / BASELINE_RESNET50_IMG_PER_SEC_PER_CHIP, 4)
            if on_tpu else None,
            "mfu": round(tput * flops_per_img / TPU_PEAK_FLOPS, 4)
            if on_tpu else None,
            "batch": batch, "image": hw,
            "backend": jax.default_backend(),
        }))
        return

    if args.model == "ernie":
        if args.smoke or not on_tpu:
            batch, seq, steps, warmup, amp = 4, 64, 3, 2, False
        else:
            batch, seq, steps, warmup, amp = 32, 512, 20, 3, True
        batch = args.batch or batch
        seq = args.seq or seq
        steps = args.steps or steps
        log(f"bench: ernie-3.0-base batch={batch} seq={seq} steps={steps} "
            f"backend={jax.default_backend()} amp={amp}")
        eng = build_ernie_engine(batch, seq, amp)
        tput = run_ernie(eng, batch, seq, steps, warmup)
        fpt = gpt_flops_per_token(eng.network, seq)  # same 6N+12Lhs conv.
        print(json.dumps({
            "metric": "ernie3_base_pretrain_tokens_per_sec_per_chip",
            "value": round(tput, 1),
            "unit": "tokens/s/chip",
            "vs_baseline": round(
                tput / BASELINE_ERNIE_TOKENS_PER_SEC_PER_CHIP, 4)
            if on_tpu else None,
            "mfu": round(tput * fpt / TPU_PEAK_FLOPS, 4) if on_tpu else None,
            "batch": batch, "seq": seq,
            "backend": jax.default_backend(),
        }))
        return

    if args.smoke or not on_tpu:
        cfg, batch, seq, steps, warmup, amp = "gpt-tiny", 4, 64, 4, 2, False
    else:
        cfg, batch, seq, steps, warmup, amp = "gpt3-345M", 8, 1024, 20, 3, True
    cfg = args.config or cfg
    batch = args.batch or batch
    seq = args.seq or seq
    steps = args.steps or steps

    use_flash = not args.no_flash
    log(f"bench: {cfg} batch={batch} seq={seq} steps={steps} "
        f"backend={jax.default_backend()} amp={amp} flash={use_flash} "
        f"recompute={args.recompute}")
    eng = build_engine(cfg, batch, seq, amp, use_flash=use_flash,
                       recompute=args.recompute)
    tput = run(eng, batch, seq, steps, warmup, scan_steps=args.scan_steps)
    print(json.dumps({
        "metric": "gpt_pretrain_tokens_per_sec_per_chip",
        "value": round(tput, 1),
        "unit": "tokens/s/chip",
        # vs_baseline compares against an A100 number — only meaningful on
        # the real chip
        "vs_baseline": round(tput / BASELINE_TOKENS_PER_SEC_PER_CHIP, 4)
        if on_tpu else None,
        "mfu": round(tput * gpt_flops_per_token(eng.network, seq)
                     / TPU_PEAK_FLOPS, 4) if on_tpu else None,
        "config": cfg, "batch": batch, "seq": seq, "flash": use_flash,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
