"""Benchmark driver: flagship GPT pretrain throughput (tokens/sec/chip).

Prints ONE JSON line per completed workload, ending with the headline
GPT result:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

The LAST stdout line is always a parseable headline JSON object (with a
`workloads` field carrying every other completed measurement), so a
later hang can never erase earlier numbers.

Architecture (post round-2 "decode-path incident", BENCHLOG.md): the
orchestrator process NEVER imports jax. Every workload — and a tiny
backend-health probe before the first one — runs in its own killable
subprocess with a hard timeout. A wedged TPU terminal therefore costs
one workload + a diagnostic, not the whole artifact.

Usage:
  python bench.py                 # full TPU suite: probe, gpt, ernie, resnet50
  python bench.py --smoke         # fast CPU smoke (gpt-tiny)
  python bench.py --model resnet50 [--batch N ...]   # single workload
  python bench.py --decode        # opt-in decode bench (never default)
ref parity: tools/test_runner + benchmark/ in PaddlePaddle; the metric
matches BASELINE.json (tokens/sec/chip vs A100 share).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

BASELINE_TOKENS_PER_SEC_PER_CHIP = 3500.0

# Peak FLOPs for MFU denominators resolve per device kind at runtime
# (env PADDLE_TPU_PEAK_FLOPS override > observability.introspect's
# per-device-kind table — the old hardcoded v5e 197e12 lives there
# now). MFU stays reported against the bf16 peak regardless of the amp
# dtype actually used, so an fp32 run shows honestly low MFU rather
# than flattering itself. Unresolvable (CPU, no override) -> both MFU
# legs are null, never computed against a made-up peak.

BASELINE_RESNET50_IMG_PER_SEC_PER_CHIP = 2900.0  # SURVEY §6: A100 fp16

# ERNIE-3.0-base (118M params): the reference's fleet-class A100 share,
# derived from the GPT-1.3B 3.5k tok/s baseline by the 6N FLOPs/token
# ratio (same training-efficiency assumption): 3.5k * 1.3e9/118e6
BASELINE_ERNIE_TOKENS_PER_SEC_PER_CHIP = 38500.0

# campaign artifacts dir; BENCH_CAMPAIGN_DIR redirects it so tests can
# exercise the null-run diagnostic against fixture summaries (and never
# write partials into the real campaign_out)
CAMPAIGN_OUT = (os.environ.get("BENCH_CAMPAIGN_DIR")
                or os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "campaign_out"))

# partials live under campaign_out/ date-stamped like the summaries —
# a probe-timeout diagnostic at the repo root read like a round result
PARTIAL_PATH = os.path.join(CAMPAIGN_OUT,
                            f"bench_partial_{int(time.time())}.json")


def log(*a):
    print(*a, file=sys.stderr, flush=True)
    _Watchdog.pet()


class _Watchdog:
    """In-worker guard: if the remote TPU backend wedges mid-workload
    (observed 2026-07-30: a stalled terminal-side compile hangs even
    jax.devices()), the worker fails fast with rc=3 instead of relying
    on the orchestrator's hard timeout."""

    _last = time.monotonic()
    # must exceed the longest legitimate silent stretch: a cold remote
    # compile of the 1.3B remat step can take many minutes with no output
    LIMIT_S = 900

    @classmethod
    def pet(cls):
        cls._last = time.monotonic()

    @classmethod
    def start(cls):
        def watch():
            while True:
                time.sleep(15)
                idle = time.monotonic() - cls._last
                if idle > cls.LIMIT_S:
                    print(
                        f"bench watchdog: no progress for {idle:.0f}s — "
                        "TPU backend unresponsive (see BENCHLOG.md "
                        "decode-path incident); aborting worker",
                        file=sys.stderr, flush=True)
                    os._exit(3)

        threading.Thread(target=watch, daemon=True).start()


# --------------------------------------------------------------------------
# worker-side run telemetry (docs/observability.md): every bench worker
# writes telemetry.jsonl + a final registry snapshot metrics.json into
# the stage's telemetry dir (BENCH_TELEMETRY_DIR when the campaign sets
# it per stage, else campaign_out/telemetry/<worker>), next to the
# BENCH json the orchestrator assembles. Worker-side only — these
# helpers import paddle_tpu, which the orchestrator never does.
# --------------------------------------------------------------------------

_TELEMETRY = {}


def _obs_mod(name):
    """paddle_tpu.observability.<name> WITHOUT paying the full
    paddle_tpu package import in lean workers: the probe worker is
    deliberately jax-only (time-to-first-signal measures the backend
    handshake), and the observability modules are stdlib-only by
    contract — so when the package isn't already imported, load the
    module straight from its file under a private key. Workers that
    imported paddle_tpu get the real module (same registry/tracer
    singletons the Engine publishes into)."""
    if "paddle_tpu" in sys.modules:
        import importlib
        return importlib.import_module(
            f"paddle_tpu.observability.{name}")
    key = f"_bench_obs_{name}"
    mod = sys.modules.get(key)
    if mod is None:
        import importlib.util
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "paddle_tpu", "observability", f"{name}.py")
        spec = importlib.util.spec_from_file_location(key, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[key] = mod
        spec.loader.exec_module(mod)
    return mod


def _telemetry_dir(worker):
    return (os.environ.get("BENCH_TELEMETRY_DIR")
            or os.path.join(CAMPAIGN_OUT, "telemetry", worker))


def _emit(kind, **fields):
    """One structured record into the worker's telemetry.jsonl (logger
    created lazily so the probe stays lean until it has a result)."""
    lg = _TELEMETRY.get("logger")
    if lg is None:
        worker = _TELEMETRY.get("worker")
        if worker is None:
            return None   # orchestrator process: no telemetry
        lg = _TELEMETRY["logger"] = _obs_mod(
            "telemetry").TelemetryLogger(_telemetry_dir(worker))
    return lg.emit(kind, **fields)


def _report(payload):
    """The bench output contract (one JSON line per completed workload)
    + the same record mirrored into telemetry.jsonl."""
    print(json.dumps(payload), flush=True)
    try:
        _emit("workload_result", worker=_TELEMETRY.get("worker"),
              **payload)
    except Exception as e:  # noqa: BLE001 — telemetry never kills a result
        log(f"telemetry emit failed: {e}")


def _hist_ms(h, scale=1e3):
    """Histogram rollup row (ms): the --serve ladder's latency shape,
    not just a mean."""
    if h is None or not h.count:
        return None
    return {"count": h.count,
            "mean": round(h.mean() * scale, 3),
            "p50": round(h.quantile(0.5) * scale, 3),
            "p99": round(h.quantile(0.99) * scale, 3),
            "max": round(h.max * scale, 3)}


def _finalize_worker_telemetry(worker):
    """Write the stage's metrics.json: the process-global registry
    snapshot + the recompile report, MERGED over earlier workers of the
    same stage (bench_full runs four workers into one dir). Runs in a
    finally: a failed workload still leaves its partial run facts."""
    try:
        _metrics = _obs_mod("metrics")
        MetricsRegistry = _metrics.MetricsRegistry
        get_registry = _metrics.get_registry
        report_all = _obs_mod("trace").report_all
        lg = _TELEMETRY.get("logger")
        if lg is None:
            _emit("run_end", worker=worker)   # creates the logger
            lg = _TELEMETRY.get("logger")
            if lg is None:
                return
        else:
            lg.emit("run_end", worker=worker,
                    records=lg.records)
        lg.flush()
        lg.close()
        rep = report_all()
        for t in rep["tracers"]:
            t["worker"] = worker
        workers = [worker]
        merged = MetricsRegistry()
        path = os.path.join(lg.run_dir, "metrics.json")
        # merge an earlier snapshot ONLY if it came from THIS bench
        # invocation (the orchestrator stamps one BENCH_RUN_ID and
        # multi-worker stages share a dir). Any re-invocation — direct
        # or with BENCH_TELEMETRY_DIR pointed at a persisting dir —
        # gets a fresh id and overwrites: merging across runs would
        # compound stale counters and carry a historical unexpected
        # retrace into every future report.
        run_id = os.environ.get("BENCH_RUN_ID")
        if run_id is not None and os.path.exists(path):
            try:
                with open(path) as f:
                    old = json.load(f)
                if old.get("run_id") == run_id:
                    merged.merge(old)
                    oldrep = old.get("recompile_report") or {}
                    rep["tracers"] = (oldrep.get("tracers") or []) \
                        + rep["tracers"]
                    rep["unexpected_retraces"] += oldrep.get(
                        "unexpected_retraces", 0)
                    workers = (old.get("workers") or []) + workers
            except (OSError, ValueError, KeyError,
                    json.JSONDecodeError):
                pass  # a torn earlier snapshot must not lose this one
        merged.merge(get_registry().snapshot())
        merged.dump(path, extra={"recompile_report": rep,
                                 "workers": workers,
                                 "run_id": run_id})
        log(f"telemetry: {os.path.relpath(lg.path)} + "
            f"{os.path.relpath(path)}")
    except Exception as e:  # noqa: BLE001
        log(f"telemetry finalize failed: {e}")


# --------------------------------------------------------------------------
# worker-side workloads (only these import jax; orchestrator never does)
# --------------------------------------------------------------------------

def count_params(model):
    import numpy as np
    return int(sum(np.prod(p.shape) for p in model.parameters()))


def gpt_flops_per_token(model, seq):
    """Training FLOPs/token: 6*N for the dense matmuls (fwd+bwd) plus the
    attention score/value matmuls 12*L*h*s (fwd+bwd, causal halving
    ignored to stay comparable with the usual convention)."""
    cfg = model.config
    n = count_params(model)
    return 6 * n + 12 * cfg.num_hidden_layers * cfg.hidden_size * seq


def mfu_fields(tput, units_per_call, analytic_flops_per_unit,
               sites=("train_step",)):
    """The MFU stanza every training workload reports
    (docs/observability.md "analytic vs measured"):

    - ``mfu``            analytic convention (hand-derived FLOPs/unit x
                         throughput / peak) — comparable across rounds;
    - ``mfu_measured``   what XLA actually compiled: the train-step
                         executable's cost_analysis FLOPs over the
                         measured per-call wall (units_per_call /
                         tput), same peak. Null where cost analysis is
                         unavailable (backend reports no flops key, or
                         introspection skipped/disabled);
    - ``peak_flops_used`` / ``peak_flops_source`` — the resolved
                         denominator, so both numbers are auditable.

    Drift between the two legs is the signal, not an error: the
    analytic convention ignores what XLA fused, rematerialized or
    skipped — and XLA's cost model counts a lax.scan body ONCE
    regardless of trip count, so scan-shaped sites (train_step_multi,
    scan_layers stacks) read K/L-fold low on the measured leg
    (docs/observability.md "Loop caveat")."""
    intro = _obs_mod("introspect")
    peak, src = intro.resolve_peak_flops()
    out = {"mfu": None, "mfu_measured": None,
           "peak_flops_used": peak, "peak_flops_source": src}
    if not peak or not tput:
        return out
    out["mfu"] = round(tput * analytic_flops_per_unit / peak, 4)
    seconds_per_call = units_per_call / tput
    for site in sites:
        e = intro.site_cost(site, tracer="engine")
        if e and e.get("flops"):
            out["mfu_measured"] = round(
                e["flops"] / seconds_per_call / peak, 4)
            out["measured_flops_site"] = site
            break
    return out


def build_engine(cfg_name, batch, seq, amp, use_flash=True, recompute=False,
                 moment_dtype=None, scan_layers=False, fused_qkv=False,
                 fused_ln=False, chunked_ce=0, fused_adamw=False):
    import jax.numpy as jnp
    from paddle_tpu.nlp.gpt import (GPTForCausalLM, GPT_CONFIGS,
                                    GPTPretrainingCriterion, _resolve_config)
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.optimizer import AdamW

    max_pos = max(GPT_CONFIGS[cfg_name]["max_position_embeddings"], seq)
    model = GPTForCausalLM(_resolve_config(
        cfg_name, max_position_embeddings=max_pos,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        use_flash_attention=use_flash, recompute=recompute,
        scan_layers=scan_layers, fused_qkv=fused_qkv,
        fused_ln=fused_ln, chunked_ce=chunked_ce))
    model.train()
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01,
                parameters=model.parameters(), moment_dtype=moment_dtype,
                fused_kernel=fused_adamw)
    eng = Engine(model, loss=GPTPretrainingCriterion(), optimizer=opt,
                 amp_dtype=jnp.bfloat16 if amp else None)
    return eng


def run(eng, batch, seq, steps, warmup, scan_steps=0):
    import jax
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.default_rng(0)
    vocab = eng.network.config.vocab_size
    ids = jnp.asarray(rng.integers(0, vocab, (batch, seq)), dtype=jnp.int32)
    labels = jnp.asarray(rng.integers(0, vocab, (batch, seq)),
                         dtype=jnp.int32)
    log("compiling + warmup ...")
    for i in range(warmup):
        t = time.perf_counter()
        loss, _ = eng.train_batch([ids], [labels])
        # float() forces a device->host transfer: the only reliable sync on
        # the axon remote backend, where block_until_ready returns early
        float(loss)
        log(f"  warmup step {i}: {time.perf_counter() - t:.2f}s")
    log(f"warmup done, loss={float(loss):.4f}")
    if scan_steps:
        # amortize the per-dispatch tunnel latency (~6 ms on axon): K
        # real optimizer steps per compiled call — the public
        # Engine.train_batch_multi (this bench construction, promoted)
        k = int(scan_steps)
        ids_k = jnp.broadcast_to(ids, (k,) + ids.shape)
        labels_k = jnp.broadcast_to(labels, (k,) + labels.shape)
        losses, _ = eng.train_batch_multi([ids_k], [labels_k])  # compile
        float(losses[-1])
        t0 = time.perf_counter()
        calls = max(1, steps // k)
        for _ in range(calls):
            losses, _ = eng.train_batch_multi([ids_k], [labels_k])
            _Watchdog.pet()
        float(losses[-1])
        dt = time.perf_counter() - t0
        return batch * seq * k * calls / dt
    t0 = time.perf_counter()
    for i in range(steps):
        loss, _ = eng.train_batch([ids], [labels])
        _Watchdog.pet()  # dispatch is async: a healthy backend returns fast
    # the param-donation chain makes the last loss depend on every step, so
    # one final sync times the whole window
    float(loss)
    dt = time.perf_counter() - t0
    return batch * seq * steps / dt


def build_ernie_engine(batch, seq, amp, fused_qkv=False, fused_ln=False,
                       mlm_gather=0.0):
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.nlp import (ErnieForPretraining,
                                ErniePretrainingCriterion)
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.optimizer import AdamW

    from paddle_tpu.nlp.ernie import ERNIE_CONFIGS
    from paddle_tpu.nlp.ernie import _resolve_config as _ernie_cfg
    paddle.seed(0)
    max_pos = max(ERNIE_CONFIGS["ernie-3.0-base-zh"]
                  ["max_position_embeddings"], seq)
    model = ErnieForPretraining(_ernie_cfg(
        "ernie-3.0-base-zh", max_position_embeddings=max_pos,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        fused_qkv=fused_qkv, fused_ln=fused_ln,
        mlm_gather_capacity=mlm_gather))
    model.train()
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01,
                parameters=model.parameters())
    return Engine(model, loss=ErniePretrainingCriterion(), optimizer=opt,
                  amp_dtype=jnp.bfloat16 if amp else None)


def run_ernie(eng, batch, seq, steps, warmup):
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.default_rng(0)
    vocab = eng.network.config.vocab_size
    ids = jnp.asarray(rng.integers(0, vocab, (batch, seq)), dtype=jnp.int32)
    # MLM labels: 15% masked positions carry the target id, rest -100
    lbl = np.where(rng.random((batch, seq)) < 0.15,
                   rng.integers(0, vocab, (batch, seq)), -100)
    labels = jnp.asarray(lbl, dtype=jnp.int32)
    nsp = jnp.asarray(rng.integers(0, 2, (batch,)), dtype=jnp.int32)
    log("compiling + warmup (ernie) ...")
    for _ in range(warmup):
        loss, _ = eng.train_batch([ids], [labels, nsp])
        float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, _ = eng.train_batch([ids], [labels, nsp])
        _Watchdog.pet()
    float(loss)
    return batch * seq * steps / (time.perf_counter() - t0)


def _resnet_layout(layout, fused_bottleneck):
    """CLI spelling -> model layout. --fused-bottleneck implies NHWC
    when the layout is left on auto (the kernel is channels-last only,
    and 'auto' resolves to NCHW off-TPU where the smoke runs live)."""
    lay = {"auto": "auto", "nhwc": "NHWC", "nchw": "NCHW"}[layout or "auto"]
    if fused_bottleneck and lay == "auto":
        lay = "NHWC"
    return lay


def build_resnet_engine(amp, s2d=False, layout="auto",
                        fused_bottleneck=False):
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50(num_classes=1000, s2d_stem=s2d, layout=layout,
                     fused_bottleneck=fused_bottleneck)
    model.train()
    opt = paddle.optimizer.Momentum(0.1, momentum=0.9,
                                    parameters=model.parameters())
    return Engine(model, loss=paddle.nn.CrossEntropyLoss(), optimizer=opt,
                  amp_dtype=jnp.bfloat16 if amp else None)


def run_resnet(eng, batch, steps, warmup, hw=224):
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 3, hw, hw)),
                    dtype=jnp.float32)
    y = jnp.asarray(rng.integers(0, 1000, (batch,)))
    log("compiling + warmup (resnet50) ...")
    for i in range(warmup):
        loss, _ = eng.train_batch([x], [y])
        float(loss)  # real sync (see run())
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, _ = eng.train_batch([x], [y])
        _Watchdog.pet()
    float(loss)
    return batch * steps / (time.perf_counter() - t0)


def _maybe_enable_bench_cache(worker):
    """Opt-in persistent XLA compilation cache for bench workers
    (PADDLE_TPU_BENCH_CACHE=<dir>): cuts the driver's time-to-first-
    metric by reloading warm executables instead of recompiling
    (VERDICT r5 #2). Guard: on jax 0.4.x, RELOADING an executable with
    donated buffers aborts jaxlib (deterministic SIGSEGV — the r6 test
    suite crash, R6_NOTES.md), so the cache only arms for workloads
    whose programs donate nothing (probe/decode) or that know to switch
    donation off when the cache is armed (serve — see worker_serve);
    the donating Engine train workloads stay cold on old jax.

    Returns True when the cache was enabled (serve uses this to drop
    page-pool donation)."""
    d = os.environ.get("PADDLE_TPU_BENCH_CACHE")
    if not d:
        return False
    import jax
    try:
        ver = tuple(int(p) for p in jax.__version__.split(".")[:2])
    except ValueError:
        ver = (0, 0)
    if ver < (0, 6) and worker not in ("probe", "decode", "serve"):
        log(f"bench cache: NOT armed for {worker!r} on jax "
            f"{jax.__version__} (donated-executable reload aborts "
            "jaxlib 0.4.x — R6_NOTES.md)")
        return False
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    # the donation hazard is 0.4.x-only: on modern jax, serve keeps its
    # in-place page-pool updates even with the cache armed
    _BENCH_CACHE_ARMED["donate_unsafe"] = ver < (0, 6)
    log(f"bench cache armed at {d} for {worker!r}")
    return True


def worker_probe():
    """Backend health check: the smallest possible end-to-end compile +
    execute + device->host sync. Run in a subprocess with a timeout by
    the orchestrator; a wedged terminal hangs here, not in a workload.
    The graph is deliberately MINIMAL (one elementwise reduce over a
    single 8x128 tile — the smallest legal TPU tile) so time-to-first-
    signal is dominated by the backend handshake, not the compile."""
    t0 = time.perf_counter()
    import jax
    import jax.numpy as jnp
    backend = jax.default_backend()
    n = len(jax.devices())
    x = jnp.ones((8, 128), jnp.bfloat16)
    s = float((x * 2).sum())  # forces compile + transfer
    _report({
        "probe": "ok", "backend": backend, "devices": n,
        "result": s, "seconds": round(time.perf_counter() - t0, 1),
    })


def worker_decode(args, on_tpu):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.nlp.generation import generate
    import numpy as np
    if args.smoke or not on_tpu:
        cfg, batch, new_tok = "gpt-tiny", 2, 16
    else:
        cfg, batch, new_tok = "gpt2-en", 8, 128
    cfg = args.config or cfg
    batch = args.batch or batch
    use_flash = on_tpu and not args.no_flash
    # the Pallas decode kernel additionally sits behind an env gate (see
    # ops/attention.py flash_decode) — report what actually ran
    flash_kernel = (use_flash and
                    os.environ.get("PADDLE_TPU_FLASH_DECODE") == "1")
    model = GPTForCausalLM(_resolve_config(
        cfg, max_position_embeddings=1024, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
        use_flash_attention=use_flash))
    model.eval()
    if args.serve_dtype:
        # the simplest rung of the serving ladder: cast every weight to
        # bf16 — halves the per-token HBM weight stream vs fp32
        model = model.to(dtype=args.serve_dtype)
        log(f"serving weights cast to {args.serve_dtype}")
    if args.weight_only:
        from paddle_tpu.nn.quant import quantize_for_serving
        n = quantize_for_serving(model, weight_dtype=args.weight_only)
        log(f"weight-only {args.weight_only}: {n} layers converted")
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size
    prompt = jnp.asarray(rng.integers(0, vocab, (batch, 64)), jnp.int32)
    log(f"bench decode: {cfg} batch={batch} new_tokens={new_tok} "
        f"flash={use_flash}")
    cache_dt = args.cache_dtype or "float32"
    out = generate(model, prompt, max_new_tokens=new_tok,
                   cache_dtype=cache_dt)  # compile
    float(jnp.sum(out._value if hasattr(out, "_value") else out))
    log("decode compiled; timing ...")
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = generate(model, prompt, max_new_tokens=new_tok,
                       cache_dtype=cache_dt)
        _Watchdog.pet()
    float(jnp.sum(out._value if hasattr(out, "_value") else out))
    dt = (time.perf_counter() - t0) / reps
    _report({
        "metric": "gpt_decode_tokens_per_sec_per_chip",
        "value": round(batch * new_tok / dt, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": None,
        "config": cfg, "batch": batch, "new_tokens": new_tok,
        "ms_per_step": round(dt / new_tok * 1e3, 2),
        "flash": use_flash, "flash_kernel": flash_kernel,
        "weight_only": args.weight_only,
        "serve_dtype": args.serve_dtype,
        "cache_dtype": cache_dt,
        "backend": jax.default_backend(),
    })


SERVE_DTYPES = ("float32", "bfloat16", "int8")


def _serve_ladder(on_tpu, smoke):
    """(batch, cache_dtype, flash) rungs. TPU: the full cross product
    batch 1/8/32 x fp32/bf16/int8 x flash off/on. CPU smoke: every axis
    still covered (flash rungs run the identical Pallas kernel in
    interpret mode) but the cross product is pruned to keep the dryrun
    inside the smoke timeout."""
    if not smoke and on_tpu:
        return [(b, d, f) for b in (1, 8, 32) for d in SERVE_DTYPES
                for f in (False, True)]
    return ([(b, d, False) for b in (1, 8) for d in SERVE_DTYPES]
            + [(8, d, True) for d in SERVE_DTYPES]
            + [(32, "float32", False)])


def _serve_model(kind, on_tpu, smoke):
    if kind == "llama":
        from paddle_tpu.nlp.llama import LlamaForCausalLM, LlamaConfig
        if smoke or not on_tpu:
            # GQA (2 kv heads for 4 query heads) + head_dim 64 so the
            # paged Pallas kernel gate accepts the flash rungs
            cfg = LlamaConfig(vocab_size=256, hidden_size=256,
                              num_hidden_layers=2, num_attention_heads=4,
                              num_key_value_heads=2,
                              intermediate_size=256,
                              max_position_embeddings=512)
        else:
            from paddle_tpu.nlp.llama import _resolve_config as _llama_cfg
            cfg = _llama_cfg("llama-1b")
        return LlamaForCausalLM(cfg), "llama"
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    if smoke or not on_tpu:
        # heads=1 -> head_dim 64: the CPU flash rungs exercise the real
        # kernel (interpret mode) instead of silently falling back
        cfg = _resolve_config("gpt-tiny", num_attention_heads=1)
    else:
        cfg = _resolve_config("gpt2-en", hidden_dropout_prob=0.0,
                              attention_probs_dropout_prob=0.0)
    return GPTForCausalLM(cfg), "gpt"


def worker_serve(args, on_tpu):
    """Continuous-batching serving ladder (paddle_tpu.nlp.serving):
    per rung, one warmup wave compiles the (bucket, strategy) programs,
    then a timed wave of 2x max_slots requests runs through admission /
    decode / eviction with the compile counters asserted FROZEN — a
    recompiling steady state fails the rung loudly instead of timing
    compiles (the r4 decode-scalar mistake)."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp.serving import ServingEngine
    from paddle_tpu.observability.metrics import (MetricsRegistry,
                                                  get_registry)

    smoke = args.smoke or not on_tpu
    paddle.seed(0)
    model, kind = _serve_model(args.serve_model, on_tpu, args.smoke)
    vocab = model.config.vocab_size
    if smoke:
        page_size, max_seq, new_tok, spd = 16, 48, 8, 2
        prompt_lens = (10, 12, 15, 13)
    else:
        # max_seq 256 = 2 pages/slot: the b32 fp32 rung's pool stays
        # ~6GB (129 pages x 128 x H x D x 4B x k,v x L would be 2x
        # that at 512 — too close to the 16GB chip with weights)
        page_size, max_seq, new_tok, spd = 128, 256, 128, 16
        prompt_lens = (96, 120, 64, 100)
    # donated page pools + persistent cache don't mix on jax 0.4.x
    # (reloading a donated executable aborts — R6_NOTES.md); on
    # modern jax donation stays ON so the cached A/B measures the
    # same in-place page-pool updates as the cache-off run
    donate = not (_BENCH_CACHE_ARMED.get("on")
                  and _BENCH_CACHE_ARMED.get("donate_unsafe"))
    ladder = _serve_ladder(on_tpu, smoke)
    if args.batch:
        ladder = [r for r in ladder if r[0] == args.batch]
    if args.cache_dtype:
        ladder = [r for r in ladder if r[1] == args.cache_dtype]
    if args.no_flash:
        ladder = [r for r in ladder if not r[2]]
    if args.flash_only:
        # the bench_serve_flashk stage: only the kernel rungs — the ref
        # rungs already rode bench_serve_gpt's window
        ladder = [r for r in ladder if r[2]]
    rng = np.random.default_rng(0)
    rows, skipped = [], []
    for batch, dtype, flash in ladder:
        tag = f"b{batch}/{dtype}/{'flash' if flash else 'ref'}"
        if flash and on_tpu and \
                os.environ.get("PADDLE_TPU_FLASH_DECODE") != "1":
            # same caution as bench_decode_flashk: the hardware kernel
            # arms only after decode_probe proves it (r2 wedge)
            skipped.append(tag)
            continue
        use_flash = True if flash else False
        # per-rung private registry: warmup publishes then
        # reset_counters() zeroes it, so the histograms below cover
        # exactly the timed wave; merged into the process registry
        # after the rung, which is how the stage's metrics.json holds
        # the ladder-wide latency shape
        rung_reg = MetricsRegistry()
        eng = ServingEngine(model, max_slots=batch, page_size=page_size,
                            max_seq_len=max_seq, cache_dtype=dtype,
                            use_flash=use_flash,
                            steps_per_dispatch=spd, donate=donate,
                            registry=rung_reg,
                            spec_decode=bool(args.spec),
                            # per-rung HBM attribution: the ladder's
                            # peak per-segment numbers ride the same
                            # registry merge as the latency shape
                            mem_ledger=True)
        if args.spec:
            # the verify program only arms through warmup() (the
            # zero-recompile gate) — the wave-as-warmup below never
            # traces it, so an unwarmed --spec rung would silently
            # measure plain decode
            eng.warmup(buckets=sorted(set(prompt_lens)), decode=True)
        def wave(n):
            prompts = [rng.integers(0, vocab,
                                    (prompt_lens[i % len(prompt_lens)],))
                       for i in range(n)]
            return eng.generate(prompts, max_new_tokens=new_tok)
        wave(batch)  # warmup: compiles the rung's programs
        frozen = eng.compile_counts()
        eng.reset_counters()
        t0 = time.perf_counter()
        # steady state incl. admission/recycling; small-batch rungs get
        # extra requests so the timed window holds enough dispatches
        # for a stable number on a noisy host
        out = wave(max(2 * batch, 32))
        wall = time.perf_counter() - t0
        _Watchdog.pet()
        after = eng.compile_counts()
        recompiles = sum(after.values()) - sum(frozen.values())
        if recompiles:
            raise RuntimeError(
                f"serve rung {tag}: {recompiles} recompile(s) in steady "
                f"state ({frozen} -> {after}) — the single-program "
                "contract is broken")
        toks = sum(len(t) for t in out)
        # headline per rung = batched-DECODE throughput (the engine's
        # dispatch counters); wall-clock additionally pays the batch-1
        # prefill admissions, reported alongside
        dec_s = max(eng.decode_seconds, 1e-9)
        row = {"batch": batch, "cache_dtype": dtype, "flash": flash,
               # what actually ran: the gate can refuse a forced kernel
               # on unsupported shapes (worker_decode's flash vs
               # flash_kernel precedent) — never mislabel a ref rung
               "flash_kernel": eng.use_flash,
               "tok_s": round(eng.decode_tokens / dec_s, 1),
               "ms_per_tok": round(dec_s / max(eng.decode_tokens, 1)
                                   * 1e3, 3),
               "wall_tok_s": round(toks / wall, 1),
               "decode_dispatches": eng.decode_dispatches,
               "steady_recompiles": 0,
               # the latency SHAPE, not just the mean (the ladder's
               # p99 is the serving number a deployment pages on)
               "decode_tok_ms": _hist_ms(
                   rung_reg.get("serve_decode_token_seconds")),
               "ttft_ms": _hist_ms(rung_reg.get("serve_ttft_seconds")),
               "queue_wait_ms": _hist_ms(
                   rung_reg.get("serve_queue_wait_seconds"))}
        if args.spec:
            sp = eng.health().get("spec") or {}
            row["spec"] = {"k": sp.get("k"),
                           "draft": sp.get("draft"),
                           "proposed": sp.get("proposed"),
                           "accepted": sp.get("accepted"),
                           "acceptance_rate": sp.get("acceptance_rate")}
        if eng.ledger is not None:
            mdg = eng.ledger.digest()
            row["mem"] = {
                # peak (high-watermark) + per-segment attribution:
                # THE capacity-planning numbers a rung exists to
                # produce — how many bytes each batch/dtype point
                # actually costs, split by owner
                "high_watermark_bytes": mdg.get("high_watermark_bytes"),
                "attributed_bytes": mdg.get("attributed_bytes"),
                "unattributed_bytes": mdg.get("unattributed_bytes"),
                "segments": mdg.get("segments"),
                "used_ratio": mdg.get("used_ratio")}
        rows.append(row)
        try:
            _emit("serve_rung", model=kind, **row)
        except Exception as e:  # noqa: BLE001 — telemetry never kills a result
            log(f"telemetry emit failed: {e}")
        get_registry().merge(rung_reg.snapshot())
        mem = row.get("mem") or {}
        log(f"serve {tag}: {row['tok_s']} tok/s decode "
            f"({row['wall_tok_s']} wall; {toks} toks), recompiles 0, "
            f"p99 {((row['decode_tok_ms'] or {}).get('p99'))} ms/tok, "
            f"hbm peak {mem.get('high_watermark_bytes')} B "
            f"(kv {((mem.get('segments') or {}).get('kv_pages'))})")
        del eng
    by_rung = {(r["batch"], r["cache_dtype"], r["flash"]): r["tok_s"]
               for r in rows}
    b1 = by_rung.get((1, "float32", False))
    b8 = by_rung.get((8, "float32", False))
    speedup = round(b8 / b1, 2) if b1 and b8 else None
    best = max(rows, key=lambda r: r["tok_s"]) if rows else None
    _report({
        "metric": f"serve_{kind}_decode_tokens_per_sec_per_chip",
        "value": best["tok_s"] if best else None,
        "unit": "tokens/s/chip", "vs_baseline": None,
        "model": kind, "page_size": page_size, "max_seq_len": max_seq,
        "steps_per_dispatch": spd, "new_tokens": new_tok,
        "b8_vs_b1_speedup": speedup,
        "steady_recompiles": 0,
        "decode_tok_ms": best["decode_tok_ms"] if best else None,
        "ttft_ms": best["ttft_ms"] if best else None,
        "ladder": rows, "skipped_rungs": skipped,
        "backend": jax.default_backend(),
    })


def worker_llama(args, on_tpu):
    """Llama pretrain throughput (the zoo's GQA flagship — the bench
    presence VERDICT r5 missing #4 called out)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.nlp.llama import (LlamaForCausalLM,
                                      LlamaPretrainingCriterion,
                                      _resolve_config)
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.optimizer import AdamW

    if args.smoke or not on_tpu:
        cfg, batch, seq, steps, warmup, amp = ("llama-tiny", 4, 64, 3, 2,
                                               False)
    else:
        cfg, batch, seq, steps, warmup, amp = ("llama-1b", 4, 1024, 10, 2,
                                               True)
    cfg = args.config or cfg
    batch = args.batch or batch
    seq = args.seq or seq
    steps = args.steps or steps
    use_flash = not args.no_flash
    # the 1.1B flagship needs the same memory levers as gpt3-1.3B to
    # fit one 16GB chip: bf16 Adam moments + per-block remat
    big = cfg == "llama-1b" and not args.smoke and on_tpu
    moment_dtype = args.moment_dtype or ("bfloat16" if big else None)
    recompute = args.recompute or big
    log(f"bench: {cfg} batch={batch} seq={seq} steps={steps} "
        f"backend={jax.default_backend()} amp={amp} flash={use_flash} "
        f"recompute={recompute} moment_dtype={moment_dtype}")
    paddle.seed(0)
    model = LlamaForCausalLM(_resolve_config(
        cfg, use_flash_attention=use_flash, recompute=recompute))
    model.train()
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01,
                parameters=model.parameters(),
                moment_dtype=moment_dtype)
    eng = Engine(model, loss=LlamaPretrainingCriterion(), optimizer=opt,
                 amp_dtype=jnp.bfloat16 if amp else None)
    tput = run(eng, batch, seq, steps, warmup)
    fpt = gpt_flops_per_token(eng.network, seq)  # same 6N+12Lhs conv.
    _report({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tput, 1), "unit": "tokens/s/chip",
        "vs_baseline": None,
        **mfu_fields(tput, batch * seq, fpt),
        "config": cfg, "batch": batch, "seq": seq, "flash": use_flash,
        "backend": jax.default_backend(),
    })


def worker_resnet(args, on_tpu):
    import jax
    if args.smoke or not on_tpu:
        batch, steps, warmup, amp, hw = 4, 3, 2, False, 64
    else:
        batch, steps, warmup, amp, hw = 256, 20, 3, True, 224
    batch = args.batch or batch
    steps = args.steps or steps
    if args.serve:
        return _resnet_serve(args, on_tpu, batch, steps, hw)
    layout = _resnet_layout(args.layout, args.fused_bottleneck)
    log(f"bench: resnet50 batch={batch} hw={hw} steps={steps} "
        f"backend={jax.default_backend()} amp={amp} s2d={args.s2d} "
        f"layout={layout} fused_bottleneck={args.fused_bottleneck}")
    eng = build_resnet_engine(amp, s2d=args.s2d, layout=layout,
                              fused_bottleneck=args.fused_bottleneck)
    tput = run_resnet(eng, batch, steps, warmup, hw)
    # 4.1 GFLOP fwd inference at 224px, x3 for fwd+bwd; scaled for
    # smaller images
    flops_per_img = 3 * 4.1e9 * (hw / 224.0) ** 2
    _report({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(tput, 1),
        "unit": "images/s/chip",
        # vs_baseline compares against an A100 number — meaningless for
        # a CPU smoke run, so only reported on TPU
        "vs_baseline": round(
            tput / BASELINE_RESNET50_IMG_PER_SEC_PER_CHIP, 4)
        if on_tpu else None,
        **mfu_fields(tput, batch, flops_per_img),
        "batch": batch, "image": hw, "s2d_stem": args.s2d,
        "layout": eng.network._layout,
        "fused_bottleneck": bool(args.fused_bottleneck),
        "backend": jax.default_backend(),
    })


def _resnet_serve(args, on_tpu, batch, steps, hw):
    """Inference img/s; --fold-bn applies the conv_bn_fuse_pass
    equivalent (incubate.fuse_conv_bn) before jit — one fewer
    elementwise HBM pass per conv at serving."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nn.layer import functional_call
    from paddle_tpu.tensor import Tensor
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    layout = _resnet_layout(args.layout, args.fused_bottleneck)
    model = resnet50(layout=layout,
                     fused_bottleneck=args.fused_bottleneck)
    model.eval()
    folded = 0
    if args.fold_bn:
        from paddle_tpu.incubate import fuse_conv_bn
        model, folded = fuse_conv_bn(model)
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    if on_tpu:
        model.to(dtype=dtype)
    params, buffers = model.raw_state()
    log(f"bench: resnet50 SERVE batch={batch} hw={hw} steps={steps} "
        f"fold_bn={args.fold_bn} (folded {folded} pairs) "
        f"layout={model._layout}")

    @jax.jit
    def fwd(params, buffers, x):
        out = functional_call(model, params, buffers, Tensor(x))
        return out._value if isinstance(out, Tensor) else out

    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (batch, 3, hw, hw)), dtype)
    fwd(params, buffers, x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fwd(params, buffers, x)
        _Watchdog.pet()
    float(out.sum())
    dt = time.perf_counter() - t0
    tput = batch * steps / dt
    _report({
        "metric": "resnet50_serve_images_per_sec_per_chip",
        "value": round(tput, 1), "unit": "images/s/chip",
        "vs_baseline": None, "fold_bn": bool(args.fold_bn),
        "folded_pairs": folded, "batch": batch, "image": hw,
        "layout": model._layout,
        "fused_bottleneck": bool(args.fused_bottleneck),
        "backend": jax.default_backend(),
    })


def worker_ernie(args, on_tpu):
    import jax
    if args.smoke or not on_tpu:
        batch, seq, steps, warmup, amp = 4, 64, 3, 2, False
    else:
        batch, seq, steps, warmup, amp = 32, 512, 20, 3, True
    batch = args.batch or batch
    seq = args.seq or seq
    steps = args.steps or steps
    log(f"bench: ernie-3.0-base batch={batch} seq={seq} steps={steps} "
        f"backend={jax.default_backend()} amp={amp} "
        f"fused_qkv={args.fused_qkv}")
    eng = build_ernie_engine(batch, seq, amp, fused_qkv=args.fused_qkv,
                             fused_ln=args.fused_ln,
                             mlm_gather=args.mlm_gather)
    tput = run_ernie(eng, batch, seq, steps, warmup)
    fpt = gpt_flops_per_token(eng.network, seq)  # same 6N+12Lhs conv.
    _report({
        "metric": "ernie3_base_pretrain_tokens_per_sec_per_chip",
        "value": round(tput, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(
            tput / BASELINE_ERNIE_TOKENS_PER_SEC_PER_CHIP, 4)
        if on_tpu else None,
        **mfu_fields(tput, batch * seq, fpt),
        "batch": batch, "seq": seq, "fused_qkv": args.fused_qkv,
        "fused_ln": args.fused_ln, "mlm_gather": args.mlm_gather, "chunked_ce": args.chunked_ce,
        "fused_adamw": args.fused_adamw,
        "backend": jax.default_backend(),
    })


def worker_gpt(args, on_tpu, big=False):
    import jax
    if args.smoke or not on_tpu:
        cfg, batch, seq, steps, warmup, amp = "gpt-tiny", 4, 64, 4, 2, False
    elif big:
        # BASELINE.json configs[3]: the 1.3B flagship on one 16GB chip —
        # needs bf16 Adam moments + remat to fit (BENCHLOG r3)
        cfg, batch, seq, steps, warmup, amp = "gpt3-1.3B", 4, 1024, 10, 2, True
    else:
        cfg, batch, seq, steps, warmup, amp = "gpt3-345M", 8, 1024, 20, 3, True
    cfg = args.config or cfg
    batch = args.batch or batch
    seq = args.seq or seq
    steps = args.steps or steps

    use_flash = not args.no_flash
    recompute = args.recompute or (big and not args.smoke and on_tpu)
    moment_dtype = "bfloat16" if (big and not args.smoke and on_tpu) else None
    if args.moment_dtype:
        moment_dtype = args.moment_dtype
    log(f"bench: {cfg} batch={batch} seq={seq} steps={steps} "
        f"backend={jax.default_backend()} amp={amp} flash={use_flash} "
        f"recompute={recompute} moment_dtype={moment_dtype} "
        f"scan_layers={args.scan_layers}")
    scan_layers = args.scan_layers
    eng = build_engine(cfg, batch, seq, amp, use_flash=use_flash,
                       recompute=recompute, moment_dtype=moment_dtype,
                       scan_layers=scan_layers, fused_qkv=args.fused_qkv,
                       fused_ln=args.fused_ln, chunked_ce=args.chunked_ce,
                       fused_adamw=args.fused_adamw)
    try:
        tput = run(eng, batch, seq, steps, warmup,
                   scan_steps=args.scan_steps)
    except Exception as e:
        # r4 campaign: the unrolled 1.3B remat program's remote-compile
        # RPC was cut off by the axon tunnel ("response body closed
        # before all bytes were read"). The scanned decoder's program is
        # ~L-fold smaller — retry once with it so a driver-run bench
        # still lands the 1.3B number instead of a null.
        msg = str(e)
        tunnel_cut = ("remote_compile" in msg or "read body" in msg
                      or "body closed" in msg)
        if args.no_scan_fallback or not (big and not scan_layers
                                         and tunnel_cut):
            raise
        log(f"unrolled {cfg} compile died in the tunnel RPC ({e!s:.120}) "
            "— retrying with scan_layers=True")
        del eng
        scan_layers = True
        eng = build_engine(cfg, batch, seq, amp, use_flash=use_flash,
                           recompute=recompute, moment_dtype=moment_dtype,
                           scan_layers=True, fused_qkv=args.fused_qkv,
                           fused_ln=args.fused_ln,
                           chunked_ce=args.chunked_ce,
                           fused_adamw=args.fused_adamw)
        tput = run(eng, batch, seq, steps, warmup,
                   scan_steps=args.scan_steps)
    fpt = gpt_flops_per_token(eng.network, seq)
    # --scan-steps compiles ONE K-step program (train_step_multi): its
    # cost analysis covers K optimizer steps, so the measured leg's
    # per-call window is K steps of tokens
    k = int(args.scan_steps or 0)
    _report({
        # the 1.3B metric name only when the 1.3B config actually ran
        # (smoke mode and --config overrides fall back to the generic one)
        "metric": ("gpt3_1p3b_pretrain_tokens_per_sec_per_chip"
                   if big and cfg == "gpt3-1.3B"
                   else "gpt_pretrain_tokens_per_sec_per_chip"),
        "value": round(tput, 1),
        "unit": "tokens/s/chip",
        # vs_baseline compares against an A100 number — only meaningful on
        # the real chip
        "vs_baseline": round(tput / BASELINE_TOKENS_PER_SEC_PER_CHIP, 4)
        if on_tpu else None,
        **mfu_fields(tput, batch * seq * (k or 1), fpt,
                     sites=(("train_step_multi",) if k
                            else ("train_step",))),
        "config": cfg, "batch": batch, "seq": seq, "flash": use_flash,
        "scan_layers": scan_layers, "fused_qkv": args.fused_qkv,
        "fused_ln": args.fused_ln, "chunked_ce": args.chunked_ce,
        "fused_adamw": args.fused_adamw,
        "backend": jax.default_backend(),
    })


def worker_input_pipeline(args, on_tpu):
    """Input-pipeline load test: decode/augment img/s per worker mode
    (inline / thread prefetch / N spawn processes) against a null
    consumer. ref: paddle's worker-process DataLoader exists exactly to
    beat the GIL on this workload; the 2,225 img/s ResNet consumer is
    the rate to beat. Steady-state: timing starts at the FIRST batch,
    so spawn+import cost (amortized over an epoch in real training)
    is excluded."""
    import multiprocessing
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.synthetic import SyntheticImageDataset

    n = 192 if args.smoke else 1536
    batch = args.batch or 32
    ds = SyntheticImageDataset(n)
    results = {}

    def timed(tag, **kw):
        dl = DataLoader(ds, batch_size=batch, shuffle=False,
                        drop_last=True, **kw)
        it = iter(dl)
        first = next(it)
        t0 = time.perf_counter()
        count = 0
        for b in it:
            count += int(b.shape[0])
        dt = time.perf_counter() - t0
        del first
        results[tag] = round(count / dt, 1)
        log(f"  {tag}: {results[tag]} img/s")

    timed("inline")
    timed("threads_2", num_workers=2)
    worker_counts = (1, 2) if args.smoke else (1, 2, 4)
    for w in worker_counts:
        timed(f"proc_{w}", num_workers=w, use_process_workers=True)
    best = max(results.values())
    _report({
        "metric": "input_pipeline_img_per_sec", "value": best,
        "unit": "img/s", "vs_baseline": round(best / 2225.0, 4),
        "host_cores": multiprocessing.cpu_count(),
        "batch": batch, "images": n, "modes": results,
        "note": "vs_baseline compares against the r4 ResNet-50 TPU "
                "consumer rate (2225 img/s); scaling needs host cores",
    })


WORKERS = {
    "gpt": lambda a, t: worker_gpt(a, t, big=False),
    "gpt-1.3b": lambda a, t: worker_gpt(a, t, big=True),
    "ernie": worker_ernie,
    "llama": worker_llama,
    "resnet50": worker_resnet,
    "decode": worker_decode,
    "serve": worker_serve,
    "input-pipeline": worker_input_pipeline,
}

# set by child mode before the worker runs; worker_serve reads it to
# drop page-pool donation when the persistent cache is armed
_BENCH_CACHE_ARMED = {}


# --------------------------------------------------------------------------
# orchestrator (jax-free)
# --------------------------------------------------------------------------

class WorkloadResult:
    def __init__(self, name, ok, data=None, error=None, seconds=0.0):
        self.name, self.ok, self.data = name, ok, data
        self.error, self.seconds = error, seconds


def _spawn(extra_args, timeout_s, tag):
    """Run `python bench.py <extra_args>` in a killable subprocess.
    stderr streams through live; stdout is captured (the JSON lines).
    Returns (rc, last_json_dict_or_None, error_string_or_None)."""
    cmd = [sys.executable, os.path.abspath(__file__)] + extra_args
    print(f"[bench] {tag}: {' '.join(extra_args)} (timeout {timeout_s}s)",
          file=sys.stderr, flush=True)
    t0 = time.monotonic()
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=None,
                            text=True, start_new_session=True)
    out_lines = []

    def pump():
        for line in proc.stdout:
            out_lines.append(line)
    th = threading.Thread(target=pump, daemon=True)
    th.start()
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # SIGKILL the whole process group: a wedged XLA client ignores
        # SIGTERM while stuck inside a compile RPC
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        proc.wait()
        th.join(timeout=5)
        return (None, None,
                f"timeout after {timeout_s}s (killed)",
                time.monotonic() - t0)
    th.join(timeout=5)
    dt = time.monotonic() - t0
    parsed = None
    for line in reversed(out_lines):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    if proc.returncode != 0:
        return (proc.returncode, parsed,
                f"worker exited rc={proc.returncode}", dt)
    return (proc.returncode, parsed, None, dt)


def _proc_starttime(pid):
    """Kernel start time of `pid` (clock ticks since boot; field 22 of
    /proc/<pid>/stat, parsed after the last ')' — comm may hold spaces).
    Returns 0 if unreadable. Single owner of the 'pid starttime'
    pidfile identity format; tools/tpu_campaign.py imports this."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            stat = f.read()
        return int(stat.rsplit(")", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return 0


def _flush_partial(results, probe):
    """Persist everything measured so far — survives any later wedge."""
    try:
        os.makedirs(os.path.dirname(PARTIAL_PATH), exist_ok=True)
        with open(PARTIAL_PATH, "w") as f:
            json.dump({
                "probe": probe,
                "workloads": {r.name: (r.data if r.ok else
                                       {"error": r.error}) for r in results},
            }, f, indent=1)
    except OSError:
        pass


DRIVER_MARKER = os.path.join(CAMPAIGN_OUT, "driver_bench_active")


def _preempt_campaign():
    """A driver-style bench run owns the chip: kill any in-flight
    campaign stage (tools/tpu_campaign.py records its pid) and leave a
    marker that makes tools/tunnel_watch.py and tpu_campaign.py hold
    off, so two processes never time the TPU simultaneously. The marker
    is removed when orchestrate() returns; its mtime bounds the hold-off
    if this process dies uncleanly."""
    pid_path = os.path.join(CAMPAIGN_OUT, "current_stage.pid")
    try:
        parts = open(pid_path).read().split()
        pid = int(parts[0])
        recorded_start = int(parts[1]) if len(parts) > 1 else 0
        # identity check: never killpg a recycled pid from a stale file.
        # The kernel starttime recorded at spawn is the strong check
        # (a recycled pid can't share it); 0 is the writer's
        # "unreadable" sentinel and legacy pid-only files omit it —
        # both fall through to the cmdline substring fallback alone.
        if recorded_start and _proc_starttime(pid) != recorded_start:
            raise ValueError("pid recycled (starttime mismatch)")
        cmdline = open(f"/proc/{pid}/cmdline", "rb").read().decode(
            "utf-8", "replace")
        if "bench.py" in cmdline or "tpu_campaign" in cmdline \
                or "decode_probe" in cmdline or "roofline" in cmdline \
                or "fusion_audit" in cmdline:
            os.killpg(pid, signal.SIGKILL)
            print(f"[bench] killed in-flight campaign stage (pgid {pid})"
                  " — driver bench takes the chip", file=sys.stderr,
                  flush=True)
    except (OSError, ValueError, IndexError, ProcessLookupError,
            PermissionError):
        pass
    try:
        os.makedirs(CAMPAIGN_OUT, exist_ok=True)
        with open(DRIVER_MARKER, "w") as f:
            f.write(str(os.getpid()))
    except OSError:
        pass


def _release_chip():
    try:
        os.remove(DRIVER_MARKER)
    except OSError:
        pass


def orchestrate(workloads, args, passthrough):
    smoke = args.smoke
    host_only = workloads == ["input-pipeline"]  # no chip involved:
    # don't preempt the campaign, don't gate on the backend probe
    if not smoke and not host_only \
            and not os.environ.get("CAMPAIGN_CHILD"):
        _preempt_campaign()
        try:
            return _orchestrate_impl(workloads, args, passthrough)
        finally:
            _release_chip()
    return _orchestrate_impl(workloads, args, passthrough,
                             skip_probe=host_only)


def _orchestrate_impl(workloads, args, passthrough, skip_probe=False):
    smoke = args.smoke
    probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT",
                                       240 if smoke else 600))
    work_timeout = int(os.environ.get("BENCH_WORK_TIMEOUT",
                                      600 if smoke else 1800))

    if skip_probe:
        probe, err, dt = {"probe": "ok", "backend": "host-only",
                          "seconds": 0.0}, None, 0.0
    else:
        rc, probe, err, dt = _spawn(["--worker", "probe"]
                                    + (["--smoke"] if smoke else []),
                                    probe_timeout, "probe")
    if probe is None or probe.get("probe") != "ok":
        # error text can embed a multi-KB backend traceback — bound it,
        # the final line must never outgrow the driver's capture
        err_text = f"backend probe failed: {err or probe}"
        diag = {
            "metric": "gpt_pretrain_tokens_per_sec_per_chip",
            "value": None, "unit": "tokens/s/chip", "vs_baseline": None,
            "error": err_text[:800],
            "probe_seconds": round(dt, 1),
        }
        # value stays null — this run measured nothing. But if an earlier
        # session DID measure through a live tunnel window, point the
        # reader at those artifacts instead of looking like three prior
        # null rounds (r4: campaign_out/summary.json holds a full suite
        # captured 2026-07-31 before the tunnel dropped again).
        import glob
        import re as _re

        def _window_key(p, summ):
            # prefer the capture epoch the campaign embeds in the JSON,
            # then the summary_<epoch>.json filename; mtime is the last
            # resort only (mtimes collapse after a git checkout)
            emb = summ.get("_captured_at", {})
            if isinstance(emb, dict) and emb.get("epoch"):
                try:
                    return int(emb["epoch"])
                except (ValueError, TypeError):
                    pass
            m = _re.search(r"summary_\D*(\d{9,})", os.path.basename(p))
            try:
                return int(m.group(1)) if m else int(os.path.getmtime(p))
            except OSError:
                return 0

        parsed_summaries = []
        for p in glob.glob(os.path.join(CAMPAIGN_OUT, "summary*.json")):
            try:
                with open(p) as f:
                    summ = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue  # one torn file must not discard the rest
            if isinstance(summ, dict):
                parsed_summaries.append((_window_key(p, summ), p, summ))
        ok_stages, stage_window, used_paths = {}, {}, []
        # later windows override
        for wkey, p, summ in sorted(parsed_summaries, key=lambda t: t[0]):
            try:
                stage_res = {k: v.get("result") for k, v in summ.items()
                             if isinstance(v, dict) and v.get("ok")
                             and v.get("result")}
            except AttributeError:
                continue
            if stage_res:
                ok_stages.update(stage_res)
                for k in stage_res:
                    stage_window[k] = wkey
                used_paths.append(os.path.relpath(p))
        if ok_stages:
            # The final line must stay COMPACT — r4's line embedded every
            # stage payload, grew past the driver's capture, and was
            # truncated mid-JSON (4th straight parsed:null). Full payload
            # goes to a file; the line carries scalars + pointers only.
            full_path = os.path.join(CAMPAIGN_OUT, "driver_diag.json")
            try:
                with open(full_path, "w") as f:
                    json.dump({"artifacts": used_paths,
                               "stages": ok_stages}, f, indent=1)
            except OSError as e:
                print(f"[bench] could not write {full_path}: {e}",
                      file=sys.stderr, flush=True)
                full_path = None
            # generate() program memoization landed early in the r5
            # session (2026-07-31 ~16:10 local): decode scalars captured
            # BEFORE it timed recompiles, not decode — presenting them
            # as headline numbers was VERDICT r5 weak #3's "misleading"
            # finding. Post-fix decode windows pass through untouched.
            decode_valid_since = 1785513600  # 2026-07-31 16:00 local
            compact, excluded_decode = {}, []
            for name, res in ok_stages.items():
                if not isinstance(res, dict):
                    continue
                if (res.get("metric") ==
                        "gpt_decode_tokens_per_sec_per_chip"
                        and stage_window.get(name, 0)
                        < decode_valid_since):
                    excluded_decode.append(name)
                    continue
                row = {k: res[k] for k in ("metric", "value", "unit",
                                           "vs_baseline", "mfu",
                                           "mfu_measured",
                                           "peak_flops_used")
                       if k in res and not isinstance(res[k],
                                                      (dict, list))}
                if row:
                    compact[name] = row
            diag["earlier_session_measurements"] = {
                "note": "measured by tools/tpu_campaign.py during "
                        "earlier live tunnel windows on this machine "
                        "(dates in BENCHLOG.md); NOT this run's "
                        "measurement",
                "artifacts": used_paths,
                "full_diag": (os.path.relpath(full_path)
                              if full_path else None),
                "headline_scalars": compact,
            }
            if excluded_decode:
                diag["earlier_session_measurements"][
                    "excluded_decode_stages"] = {
                    "stages": sorted(excluded_decode),
                    "reason": "recompile-contaminated (pre-memoization "
                              "decode loop, BENCHLOG r4) — rerun the "
                              "bench_decode_* ladder for valid numbers",
                }
            # belt-and-braces cap: shed weight until the line fits,
            # heaviest-first, re-checking after each shed
            em = diag["earlier_session_measurements"]
            for shed in ("headline_scalars", "excluded_decode_stages",
                         "artifacts", "note"):
                if len(json.dumps(diag)) <= 6000:
                    break
                em.pop(shed, None)
        print(json.dumps(diag), flush=True)
        return 2
    print(f"[bench] probe ok: backend={probe.get('backend')} "
          f"in {probe.get('seconds')}s", file=sys.stderr, flush=True)

    results = []
    headline = None
    for name in workloads:
        wargs = (["--worker", name] + (["--smoke"] if smoke else [])
                 + passthrough)
        rc, data, err, dt = _spawn(wargs, work_timeout, name)
        ok = data is not None and err is None
        results.append(WorkloadResult(name, ok, data, err, dt))
        if ok:
            # incremental flush: each result is printed the moment it
            # exists, so a later hang can't erase it
            print(json.dumps(data), flush=True)
            if headline is None and (name in ("gpt", "decode")
                                     or len(workloads) == 1):
                headline = data
        else:
            print(f"[bench] {name} FAILED: {err}", file=sys.stderr,
                  flush=True)
        _flush_partial(results, probe)
        if not ok and skip_probe:
            continue  # host-only workload: never touch the backend
        if not ok:
            # a failed workload may have wedged the terminal — reprobe
            # before burning timeout on the next one
            rc2, p2, e2, _ = _spawn(["--worker", "probe"]
                                    + (["--smoke"] if smoke else []),
                                    probe_timeout, "reprobe")
            if p2 is None or p2.get("probe") != "ok":
                print("[bench] backend wedged after failure — stopping "
                      "with partial results", file=sys.stderr, flush=True)
                break

    # final line: the headline (gpt) result, carrying all other completed
    # workloads, ALWAYS the last JSON object on stdout
    extra = {r.name: r.data for r in results if r.ok and r.data is not headline}
    failures = {r.name: r.error for r in results if not r.ok}
    if headline is not None:
        final = dict(headline)
        if extra:
            final["workloads"] = extra
        if failures:
            final["failed_workloads"] = failures
        print(json.dumps(final), flush=True)
        return 0
    # headline failed: emit a best-available final line so the artifact
    # still parses (value null signals the miss honestly)
    first = workloads[0]
    final = {
        "metric": ("gpt_pretrain_tokens_per_sec_per_chip"
                   if first in ("gpt", "decode") else first),
        "value": None, "unit": "tokens/s/chip", "vs_baseline": None,
        "error": failures.get(first) or failures.get("gpt")
        or "headline workload did not run",
    }
    if extra:
        final["workloads"] = extra
    if failures:
        final["failed_workloads"] = failures
    print(json.dumps(final), flush=True)
    return 4


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--config", default=None)
    ap.add_argument("--model", choices=tuple(WORKERS), default=None)
    ap.add_argument("--no-flash", action="store_true",
                    help="disable the Pallas flash-attention path (fallback "
                         "number if the kernel regresses)")
    ap.add_argument("--recompute", action="store_true",
                    help="rematerialize decoder blocks (enables larger "
                         "batches)")
    ap.add_argument("--moment-dtype", default=None,
                    help="Adam moment dtype override (e.g. bfloat16)")
    ap.add_argument("--serve", action="store_true",
                    help="resnet50: inference throughput instead of "
                         "training")
    ap.add_argument("--fold-bn", action="store_true",
                    help="resnet50 --serve: fold BatchNorms into conv "
                         "weights first (conv_bn_fuse_pass parity)")
    ap.add_argument("--s2d", action="store_true",
                    help="resnet50: MLPerf space-to-depth stem (exactly "
                         "equivalent 4x4/s1 conv over 12 channels)")
    ap.add_argument("--layout", choices=("auto", "nhwc", "nchw"),
                    default=None,
                    help="resnet50: conv-stack layout A/B — nhwc is the "
                         "TPU-native channels-last pipeline (ONE boundary "
                         "transpose, HWIO kernels); auto resolves to nhwc "
                         "on TPU, nchw elsewhere")
    ap.add_argument("--fused-bottleneck", action="store_true",
                    help="resnet50: route the bottleneck 1x1-conv+BN+ReLU"
                         "(+residual) chains through the Pallas fused "
                         "kernel (the diagnosed HBM-bandwidth wall; "
                         "implies nhwc while --layout is auto)")
    ap.add_argument("--dryrun", action="store_true",
                    help="alias for --smoke")
    ap.add_argument("--weight-only", choices=("int8", "int4"), default=None,
                    help="decode: serve with weight-only-quantized linears "
                         "(HBM-bandwidth lever)")
    ap.add_argument("--serve-dtype", default=None,
                    choices=("bfloat16", "float16"),
                    help="decode: cast model weights for serving "
                         "(bf16 halves the HBM weight stream)")
    ap.add_argument("--cache-dtype", default=None,
                    help="decode/serve KV cache dtype (bfloat16 halves "
                         "decode HBM traffic; serve also takes int8)")
    ap.add_argument("--spec", action="store_true",
                    help="--serve: arm speculative decoding on every "
                         "rung (ngram draft, PADDLE_TPU_SPEC_K "
                         "tokens/dispatch); rows gain the acceptance "
                         "stats and stay token-exact vs plain rungs")
    ap.add_argument("--serve-model", choices=("gpt", "llama"),
                    default="gpt",
                    help="serve: which zoo model the ladder decodes "
                         "(llama exercises GQA + RoPE paged decode)")
    ap.add_argument("--flash-only", action="store_true",
                    help="serve: run only the flash-kernel rungs (the "
                         "bench_serve_flashk stage — ref rungs already "
                         "measured by bench_serve_gpt)")
    ap.add_argument("--mlm-gather", type=float, default=0.0,
                    help="ernie: gather at most this fraction of "
                         "positions (the masked ~15%%) before the "
                         "MLM head — head FLOPs/logits shrink "
                         "~1/c-fold (0 = full head)")
    ap.add_argument("--fused-adamw", action="store_true",
                    help="gpt: one-HBM-pass Pallas optimizer update "
                         "(the 22.8ms-vs-11.8ms-floor lever)")
    ap.add_argument("--chunked-ce", type=int, default=0,
                    help="gpt: fuse the LM head into the loss over "
                         "token chunks of this size (the [N,vocab] "
                         "logits never materialize)")
    ap.add_argument("--fused-ln", action="store_true",
                    help="gpt: fuse residual add + LayerNorm into one "
                         "Pallas pass (elementwise-HBM lever)")
    ap.add_argument("--fused-qkv", action="store_true",
                    help="gpt: one [h,3h] qkv matmul (Megatron "
                         "head-interleaved) instead of three [h,h]")
    ap.add_argument("--no-scan-fallback", action="store_true",
                    help="gpt-1.3b: fail instead of retrying a tunnel-cut "
                         "unrolled compile with scan_layers (the dedicated "
                         "unrolled A/B stage wants the honest failure)")
    ap.add_argument("--scan-layers", action="store_true",
                    help="gpt: stacked-params lax.scan over decoder "
                         "layers (O(1-block) compiled program; the "
                         "1.3B remote-compile mitigation)")
    ap.add_argument("--scan-steps", type=int, default=0,
                    help="run K optimizer steps per compiled call "
                         "(lax.scan) to amortize dispatch latency")
    ap.add_argument("--input-pipeline", action="store_true",
                    help="measure decode/augment img/s per DataLoader "
                         "worker mode (inline/threads/processes) "
                         "against a null consumer")
    ap.add_argument("--decode", action="store_true",
                    help="measure KV-cache generation throughput instead "
                         "of training (opt-in; never on the default path)")
    ap.add_argument("--worker", default=None,
                    help="internal: run one workload in-process")
    ap.add_argument("--all", action="store_true",
                    help="run every workload incl. smoke mode")
    args = ap.parse_args()
    if args.dryrun:
        args.smoke = True

    # one id per bench invocation, inherited by spawned workers: the
    # telemetry finalize merges an existing metrics.json only when it
    # was written under the SAME id (multi-worker stages share a dir;
    # re-invocations overwrite instead of compounding stale counters)
    os.environ.setdefault("BENCH_RUN_ID",
                          f"{int(time.time() * 1e3)}-{os.getpid()}")

    if args.worker:
        # ---- child mode: the only place jax is imported ----
        if args.smoke:
            import _cpu_env  # noqa: F401  (axon bypass; precede jax import)
        _Watchdog.start()
        _TELEMETRY["worker"] = args.worker
        try:
            if args.worker == "input-pipeline":
                # host-side workload: never touch jax (a dead tunnel
                # would hang backend init for a bench that doesn't
                # need the chip)
                import _cpu_env  # noqa: F401
                worker_input_pipeline(args, False)
                return
            _BENCH_CACHE_ARMED["on"] = _maybe_enable_bench_cache(
                args.worker)
            if args.worker == "probe":
                worker_probe()
                return
            import jax
            on_tpu = jax.default_backend() == "tpu"
            WORKERS[args.worker](args, on_tpu)
        finally:
            # every stage leaves telemetry.jsonl + metrics.json — on
            # failure too (the partial run facts ARE the diagnostic)
            _finalize_worker_telemetry(args.worker)
        return

    # ---- orchestrator mode: jax-free ----
    if args.input_pipeline:
        workloads = ["input-pipeline"]
    elif args.decode:
        workloads = ["decode"]
    elif args.serve and args.model is None:
        # the continuous-batching serving ladder (nlp/serving.py);
        # resnet50 inference keeps its historical `--model resnet50
        # --serve` spelling
        workloads = ["serve"]
    elif args.model:
        workloads = [args.model]
    elif args.smoke and not args.all:
        workloads = ["gpt"]
    else:
        # headline first: a later hang can't erase the number that
        # matters. 1.3B runs LAST (newest path = highest wedge risk).
        workloads = ["gpt", "ernie", "resnet50", "gpt-1.3b"]

    # flags that only one workload family reads: reject elsewhere instead
    # of silently benching the default config under a tuned-looking name
    if args.weight_only and workloads != ["decode"]:
        ap.error("--weight-only applies to decode serving only "
                 "(use --decode)")
    if args.cache_dtype and workloads not in (["decode"], ["serve"]):
        ap.error("--cache-dtype applies to decode/serve only "
                 "(use --decode or --serve)")
    if args.serve_model != "gpt" and workloads != ["serve"]:
        ap.error("--serve-model applies to the serving ladder only "
                 "(use --serve)")
    if args.flash_only and workloads != ["serve"]:
        ap.error("--flash-only applies to the serving ladder only "
                 "(use --serve)")
    if args.spec and workloads != ["serve"]:
        ap.error("--spec applies to the serving ladder only "
                 "(use --serve)")
    if args.flash_only and args.no_flash:
        ap.error("--flash-only and --no-flash select disjoint rungs")
    if args.serve_dtype and workloads != ["decode"]:
        ap.error("--serve-dtype applies to decode serving only "
                 "(use --decode)")
    if args.serve_dtype and args.weight_only:
        ap.error("--serve-dtype and --weight-only are separate rungs of "
                 "the serving ladder: quantization derives its scales "
                 "from fp32 weights, so casting first would quantize "
                 "rounded values and mislabel the result")
    if args.moment_dtype and not set(workloads) <= {"gpt", "gpt-1.3b",
                                                    "llama"}:
        ap.error("--moment-dtype applies to the gpt/llama training "
                 "workloads only")
    if args.scan_layers and not set(workloads) <= {"gpt", "gpt-1.3b"}:
        ap.error("--scan-layers applies to the gpt training "
                 "workloads only")
    if args.fused_qkv and not set(workloads) <= {"gpt", "gpt-1.3b",
                                                 "ernie"}:
        ap.error("--fused-qkv applies to the gpt/ernie training "
                 "workloads only")
    if args.fused_ln and not set(workloads) <= {"gpt", "gpt-1.3b",
                                                "ernie"}:
        ap.error("--fused-ln applies to the gpt/ernie training "
                 "workloads only")
    if args.chunked_ce and not set(workloads) <= {"gpt", "gpt-1.3b"}:
        ap.error("--chunked-ce applies to the gpt training "
                 "workloads only")
    if args.fused_adamw and not set(workloads) <= {"gpt", "gpt-1.3b"}:
        ap.error("--fused-adamw applies to the gpt training "
                 "workloads only")
    if args.mlm_gather and workloads != ["ernie"]:
        ap.error("--mlm-gather applies to the ernie workload only")
    if args.fold_bn and workloads != ["resnet50"]:
        ap.error("--fold-bn applies to resnet50 serving only "
                 "(use --model resnet50 --serve)")
    if args.serve and workloads not in (["resnet50"], ["serve"]):
        ap.error("--serve runs the serving ladder (alone) or resnet50 "
                 "inference (--model resnet50 --serve)")
    if (args.layout or args.fused_bottleneck) \
            and workloads != ["resnet50"]:
        ap.error("--layout/--fused-bottleneck apply to the resnet50 "
                 "workload only (use --model resnet50)")
    if args.no_scan_fallback and workloads != ["gpt-1.3b"]:
        ap.error("--no-scan-fallback applies to the gpt-1.3b workload "
                 "only (use --model gpt-1.3b)")

    # per-workload tuning flags only make sense for a single explicit
    # workload — forwarding them to the whole suite would silently bench
    # every model at a non-standard config
    passthrough = []
    overrides = {"--steps": args.steps, "--batch": args.batch,
                 "--seq": args.seq, "--config": args.config,
                 "--moment-dtype": args.moment_dtype,
                 "--weight-only": args.weight_only,
                 "--serve-dtype": args.serve_dtype,
                 "--cache-dtype": args.cache_dtype,
                 "--serve-model": (args.serve_model
                                   if args.serve_model != "gpt"
                                   else None)}
    if len(workloads) == 1:
        for flag, val in overrides.items():
            if val is not None:
                passthrough += [flag, str(val)]
        if args.no_flash:
            passthrough.append("--no-flash")
        if args.flash_only:
            passthrough.append("--flash-only")
        if args.spec:
            passthrough.append("--spec")
        if args.recompute:
            passthrough.append("--recompute")
        if args.s2d:
            passthrough.append("--s2d")
        if args.layout:
            passthrough += ["--layout", args.layout]
        if args.fused_bottleneck:
            passthrough.append("--fused-bottleneck")
        if args.serve:
            passthrough.append("--serve")
        if args.fold_bn:
            passthrough.append("--fold-bn")
        if args.scan_steps:
            passthrough += ["--scan-steps", str(args.scan_steps)]
        if args.scan_layers:
            passthrough.append("--scan-layers")
        if args.fused_qkv:
            passthrough.append("--fused-qkv")
        if args.fused_ln:
            passthrough.append("--fused-ln")
        if args.chunked_ce:
            passthrough += ["--chunked-ce", str(args.chunked_ce)]
        if args.fused_adamw:
            passthrough.append("--fused-adamw")
        if args.mlm_gather:
            passthrough += ["--mlm-gather", str(args.mlm_gather)]
        if args.no_scan_fallback:
            passthrough.append("--no-scan-fallback")
    elif any(v is not None for v in overrides.values()) or args.no_flash \
            or args.recompute or args.scan_steps or args.s2d \
            or args.scan_layers or args.fused_qkv or args.fused_ln \
            or args.chunked_ce or args.fused_adamw or args.mlm_gather \
            or args.layout or args.fused_bottleneck:
        print("[bench] ignoring per-workload flags in full-suite mode "
              "(use --model to tune one workload)", file=sys.stderr,
              flush=True)
    sys.exit(orchestrate(workloads, args, passthrough))


if __name__ == "__main__":
    main()
