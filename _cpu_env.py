"""Dev helper: `python -c "import _cpu_env; ..."` for CPU-only runs.

Same axon-bypass as tests/conftest.py (see there for why), without the
8-device assertion so it works for quick single-device experiments too.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import jax._src.xla_bridge as xb  # noqa: E402

# the axon register hook may have set jax_platforms via config (which
# overrides the env var) — force it back
jax.config.update("jax_platforms", "cpu")
for reg in ("_backend_factories", "backend_factories"):
    d = getattr(xb, reg, None)
    if isinstance(d, dict):
        d.pop("axon", None)
