"""Train -> export (StableHLO) -> reload WITHOUT model code -> serve.

Run:  python examples/deploy_stablehlo.py
"""
try:
    import paddle_tpu  # noqa: F401 (pip install -e . makes this work)
except ModuleNotFoundError:  # running from a source checkout
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit import InputSpec


def main():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 3))

    # quick train
    opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
    rng = np.random.default_rng(0)
    X = rng.standard_normal((256, 8)).astype("float32")
    y = (X[:, 0] > 0).astype("int64") + (X[:, 1] > 0)
    lossfn = paddle.nn.CrossEntropyLoss()
    for _ in range(50):
        loss = lossfn(net(paddle.to_tensor(X)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()

    net.eval()
    ref = np.asarray(net(paddle.to_tensor(X[:4])).numpy())

    # export: a StableHLO artifact + params — the deployment format
    paddle.jit.save(net, "./deploy_out/model",
                    input_spec=[InputSpec([4, 8], "float32")])

    # reload in a fresh object graph: NO model class required
    served = paddle.jit.load("./deploy_out/model")
    out = np.asarray(served(paddle.to_tensor(X[:4])).numpy())
    assert np.allclose(out, ref, atol=1e-5)
    print("exported + reloaded; max |serve - train| =",
          float(np.abs(out - ref).max()))


if __name__ == "__main__":
    main()
