"""A small VAE trained with paddle.distribution pathwise gradients.

Run:  python examples/vae_distribution.py
"""
try:
    import paddle_tpu  # noqa: F401 (pip install -e . makes this work)
except ModuleNotFoundError:  # running from a source checkout
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import distribution as D


class VAE(nn.Layer):
    def __init__(self, d_in=32, d_hidden=64, d_z=8):
        super().__init__()
        self.enc = nn.Linear(d_in, d_hidden)
        self.mu = nn.Linear(d_hidden, d_z)
        self.log_sigma = nn.Linear(d_hidden, d_z)
        self.dec = nn.Linear(d_z, d_in)

    def forward(self, x):
        h = nn.functional.relu(self.enc(x))
        q = D.Normal(self.mu(h), paddle.exp(self.log_sigma(h)))
        z = q.rsample()                        # reparameterized draw
        recon = self.dec(z)
        kl = D.kl_divergence(q, D.Normal(0.0, 1.0)).sum(-1).mean()
        return recon, kl


def main():
    paddle.seed(0)
    net = VAE()
    opt = paddle.optimizer.Adam(1e-3, parameters=net.parameters())
    rng = np.random.default_rng(0)
    # toy data: two gaussian clusters
    data = np.concatenate([
        rng.standard_normal((256, 32)) * 0.5 + 2.0,
        rng.standard_normal((256, 32)) * 0.5 - 2.0,
    ]).astype("float32")
    xt = paddle.to_tensor(data)

    for step in range(200):
        recon, kl = net(xt)
        loss = ((recon - xt) ** 2).mean() + 1e-3 * kl
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 50 == 0:
            print(f"step {step}: elbo-loss {float(loss):.4f} "
                  f"kl {float(kl):.3f}")
    print(f"final: {float(loss):.4f}")


if __name__ == "__main__":
    main()
