"""Pretrain a GPT with hybrid parallelism (dp x mp x pp) on a device mesh.

On a real pod this uses every chip; to smoke-test on one host run:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/train_gpt_hybrid.py --dp 2 --mp 2 --pp 2
"""
try:
    import paddle_tpu  # noqa: F401 (pip install -e . makes this work)
except ModuleNotFoundError:  # running from a source checkout
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import argparse

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.mpu import shard_model
from paddle_tpu.hapi.engine import Engine
from paddle_tpu.nlp.gpt import (GPTConfig, GPTForCausalLM,
                                GPTForCausalLMPipe, GPTPretrainingCriterion)


def main():
    import jax
    from jax.sharding import Mesh

    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--mp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    args = ap.parse_args()

    devices = jax.devices()
    n = args.dp * args.mp * args.pp
    assert len(devices) >= n, f"need {n} devices, have {len(devices)}"

    cfg = GPTConfig(
        vocab_size=4096, hidden_size=256, num_hidden_layers=4,
        num_attention_heads=8, max_position_embeddings=args.seq,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        use_flash_attention=False,
    )

    if args.pp > 1:
        mesh = Mesh(np.array(devices[:n]).reshape(args.dp, args.mp, args.pp),
                    ("dp", "mp", "pp"))
        model = GPTForCausalLMPipe(cfg, mesh=mesh, n_micro=2)
    else:
        mesh = Mesh(np.array(devices[:n]).reshape(args.dp, args.mp),
                    ("dp", "mp"))
        model = GPTForCausalLM(cfg)
    model.train()
    shard_model(model, mesh)  # GSPMD placement: embeddings/mlp mp-sharded

    opt = paddle.optimizer.AdamW(1e-4, weight_decay=0.01,
                                 parameters=model.parameters())
    eng = Engine(model, loss=GPTPretrainingCriterion(), optimizer=opt,
                 mesh=mesh)

    rng = np.random.default_rng(0)
    with mesh:
        for step in range(args.steps):
            ids = rng.integers(0, cfg.vocab_size, (args.batch, args.seq))
            labels = rng.integers(0, cfg.vocab_size, (args.batch, args.seq))
            loss, _ = eng.train_batch(
                [paddle.to_tensor(ids.astype("int32"))],
                [paddle.to_tensor(labels.astype("int32"))])
            print(f"step {step}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
