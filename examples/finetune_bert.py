"""Finetune BERT for sequence classification on a synthetic text task.

Run:  python examples/finetune_bert.py
"""
try:
    import paddle_tpu  # noqa: F401 (pip install -e . makes this work)
except ModuleNotFoundError:  # running from a source checkout
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.hapi.engine import Engine
from paddle_tpu.nlp.bert import BertConfig, BertForSequenceClassification


def main():
    paddle.seed(7)
    cfg = BertConfig(vocab_size=1000, hidden_size=128, num_hidden_layers=2,
                     num_attention_heads=4, intermediate_size=256,
                     max_position_embeddings=64)
    model = BertForSequenceClassification(cfg, num_labels=2)
    model.train()
    opt = paddle.optimizer.AdamW(5e-4, parameters=model.parameters())
    eng = Engine(model, loss=paddle.nn.CrossEntropyLoss(), optimizer=opt)

    # synthetic task: class = whether token 7 appears in the sequence
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 1000, (256, 32)).astype("int32")
    labels = (ids == 7).any(axis=1).astype("int64")

    for epoch in range(3):
        perm = rng.permutation(len(ids))
        losses = []
        for i in range(0, len(ids), 32):
            sl = perm[i:i + 32]
            loss, _ = eng.train_batch(
                [paddle.to_tensor(ids[sl])],
                [paddle.to_tensor(labels[sl])])
            losses.append(float(loss))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f}")


if __name__ == "__main__":
    main()
