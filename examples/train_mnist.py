"""Train LeNet on MNIST with the high-level Model API.

Run:  python examples/train_mnist.py  (CPU or TPU; ~20 s on CPU)
"""
try:
    import paddle_tpu  # noqa: F401 (pip install -e . makes this work)
except ModuleNotFoundError:  # running from a source checkout
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import paddle_tpu as paddle
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def main():
    paddle.seed(42)
    net = LeNet()
    model = paddle.Model(net)
    model.prepare(
        paddle.optimizer.Adam(1e-3, parameters=net.parameters()),
        paddle.nn.CrossEntropyLoss(),
        Accuracy(),
    )
    model.fit(MNIST(mode="train"), epochs=2, batch_size=256, verbose=1)
    print(model.evaluate(MNIST(mode="test"), batch_size=256, verbose=0))
    model.save("./mnist_ckpt/final")


if __name__ == "__main__":
    main()
