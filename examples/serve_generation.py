"""Train a tiny GPT, quantize its weights for serving, and decode with
every generation strategy — the serving half of the framework, end to end.

Run:  python examples/serve_generation.py
"""
try:
    import paddle_tpu  # noqa: F401 (pip install -e . makes this work)
except ModuleNotFoundError:  # running from a source checkout
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.hapi.engine import Engine
from paddle_tpu.nlp import GPTConfig, GPTForCausalLM
from paddle_tpu.nlp.gpt import GPTPretrainingCriterion
from paddle_tpu.nn.quant import quantize_for_serving


def main():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                    num_attention_heads=4, max_position_embeddings=64,
                    hidden_dropout_prob=0.0,
                    attention_probs_dropout_prob=0.0,
                    use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.train()

    # a tiny periodic language: token t+1 = (t + 1) % 8 — learnable fast
    rng = np.random.default_rng(0)
    start = rng.integers(0, 8, (64, 1))
    seqs = (start + np.arange(24)[None, :]) % 8
    ids = paddle.to_tensor(seqs[:, :-1].astype("int32"))
    labels = paddle.to_tensor(seqs[:, 1:].astype("int32"))

    eng = Engine(model, loss=GPTPretrainingCriterion(),
                 optimizer=paddle.optimizer.AdamW(
                     5e-3, parameters=model.parameters(),
                     moment_dtype="bfloat16"))  # r3: half-width moments
    for step in range(80):
        loss, _ = eng.train_batch([ids], [labels])
        if step % 20 == 0:
            print(f"step {step}: loss {float(loss):.4f}")

    # ---- serving: weight-only int8 + jitted KV-cache decode ----
    model.eval()
    n = quantize_for_serving(model, weight_dtype="int8")
    print(f"quantized {n} linears to int8 for serving")

    prompt = paddle.to_tensor(np.asarray([[3, 4, 5]], np.int32))
    # bf16 KV cache: halves the decode path's dominant HBM stream
    greedy = model.generate(prompt, max_new_tokens=6, temperature=0.0,
                            cache_dtype="bfloat16")
    beam = model.generate(prompt, max_new_tokens=6, num_beams=4)
    sampled = model.generate(prompt, max_new_tokens=6, temperature=0.8,
                             top_p=0.9, seed=1)
    g = np.asarray(greedy.numpy())[0, 3:].tolist()
    print("greedy :", g)
    print("beam   :", np.asarray(beam.numpy())[0, 3:].tolist())
    print("sampled:", np.asarray(sampled.numpy())[0, 3:].tolist())
    want = [(5 + i + 1) % 8 for i in range(6)]
    print("served-model continuation correct:", g == want)


if __name__ == "__main__":
    main()
