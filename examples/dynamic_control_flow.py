"""Dy2static: tensor-dependent Python control flow under @to_static.

Three tiers, mirroring python/paddle/jit/dy2static's story:
1. simple tensor `if`/`while` — AST-lowered automatically to
   lax.cond/lax.while_loop on the first trace failure;
2. the convert_* operators used directly;
3. un-lowerable patterns — a ControlFlowError that names your function
   and spells out the cond/while_loop/where migration recipe.

Run: python examples/dynamic_control_flow.py
"""
import numpy as np

import paddle_tpu as paddle


class AdaptiveScale(paddle.nn.Layer):
    """Scales by 2 when activations run hot, 0.5 when cold — a
    data-dependent branch that cannot trace naively."""

    def __init__(self):
        super().__init__()
        self.fc = paddle.nn.Linear(8, 8)

    def forward(self, x):
        h = self.fc(x)
        if (h * h).mean() > 1.0:     # tensor-dependent: auto-lowered
            y = h * 0.5
        else:
            y = h * 2.0
        return y


@paddle.jit.to_static
def collatz_steps(n):
    """while over a traced value -> lax.while_loop."""
    steps = 0
    while n > 1:
        n = paddle.where((n % 2) == 0, n // 2, 3 * n + 1)
        steps = steps + 1
    return steps


def main():
    paddle.seed(0)
    net = paddle.jit.to_static(AdaptiveScale())
    hot = paddle.to_tensor(np.full((2, 8), 3.0, np.float32))
    cold = paddle.to_tensor(np.full((2, 8), 0.01, np.float32))
    print("hot branch mean:", float(net(hot).numpy().mean()))
    print("cold branch mean:", float(net(cold).numpy().mean()))

    n = paddle.to_tensor(np.asarray(27, np.int64))
    print("collatz(27) steps:", int(np.asarray(collatz_steps(n).numpy())))

    # tier 2: the public convert operators
    from paddle_tpu.jit.dy2static import convert_ifelse
    out = convert_ifelse(hot.sum() > 0,
                         lambda c: (c[0] + 1.0,),
                         lambda c: (c[0] - 1.0,),
                         (paddle.to_tensor(np.float32(41.0)),))
    print("convert_ifelse:", float(np.asarray(out[0].numpy()
          if hasattr(out[0], 'numpy') else out[0])))

    # tier 3: what un-lowerable control flow looks like
    @paddle.jit.to_static
    def early_return(x):
        if x.sum() > 0:
            return x * 2          # return inside a tensor branch
        return x

    try:
        early_return(hot)
    except Exception as e:
        print("\nun-lowerable pattern raises:\n", str(e)[:400], "...")


if __name__ == "__main__":
    main()
