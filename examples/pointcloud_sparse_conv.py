"""Point-cloud classification with sparse 3-D convolutions.

A miniature voxel-grid backbone (ref: the SECOND/spconv pattern that
paddle.sparse.nn serves): SubmConv3D blocks keep the active set fixed,
a strided Conv3D downsamples, and the dense head classifies. Runs
end-to-end on CPU in seconds; the gather-matmul-scatter per kernel
offset rides the MXU on TPU.

Run: python examples/pointcloud_sparse_conv.py
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import sparse


def make_cloud(rng, n_points, grid, label):
    """Synthetic shapes: class 0 = diagonal line, class 1 = plane."""
    pts = set()
    while len(pts) < n_points:
        if label == 0:
            t = rng.integers(0, grid)
            p = (t, t, int(np.clip(t + rng.integers(-1, 2), 0, grid - 1)))
        else:
            p = (int(rng.integers(0, grid)), int(rng.integers(0, grid)),
                 grid // 2)
        pts.add(p)
    coords = np.asarray([(0, *p) for p in pts], np.int64)
    feats = rng.standard_normal((len(coords), 4)).astype(np.float32)
    return coords, feats


class SparseNet(paddle.nn.Layer):
    def __init__(self, grid):
        super().__init__()
        self.c1 = sparse.nn.SubmConv3D(4, 16, 3, padding=1)
        self.c2 = sparse.nn.SubmConv3D(16, 16, 3, padding=1)
        self.down = sparse.nn.Conv3D(16, 32, 2, stride=2)
        self.head = paddle.nn.Linear(32, 2)
        self.grid = grid

    def forward(self, x):
        x = sparse.nn.ReLU()(self.c1(x))
        x = sparse.nn.ReLU()(self.c2(x))
        x = self.down(x)
        # global mean-pool over the active sites -> dense head
        feats = x.values()
        pooled = feats.mean(axis=0, keepdim=True)
        return self.head(pooled)


def main():
    rng = np.random.default_rng(0)
    grid = 8
    paddle.seed(0)
    net = SparseNet(grid)
    opt = paddle.optimizer.Adam(5e-3, parameters=net.parameters())
    loss_fn = paddle.nn.CrossEntropyLoss()

    for step in range(60):
        label = step % 2
        coords, feats = make_cloud(rng, 20, grid, label)
        x = sparse.sparse_coo_tensor(coords.T, feats,
                                     (1, grid, grid, grid, 4))
        logits = net(x)
        loss = loss_fn(logits, paddle.to_tensor(
            np.asarray([label], np.int64)))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 20 == 19:
            print(f"step {step + 1}: loss {float(loss.numpy()):.4f}")

    correct = 0
    for i in range(20):
        label = i % 2
        coords, feats = make_cloud(rng, 20, grid, label)
        x = sparse.sparse_coo_tensor(coords.T, feats,
                                     (1, grid, grid, grid, 4))
        pred = int(np.argmax(np.asarray(net(x).numpy())))
        correct += int(pred == label)
    print(f"accuracy on held-out clouds: {correct}/20")
    assert correct >= 15, "sparse backbone failed to learn"


if __name__ == "__main__":
    main()
