"""Quantization-aware training -> int8 serving, end to end.

Run:  python examples/quantize_qat.py
"""
try:
    import paddle_tpu  # noqa: F401 (pip install -e . makes this work)
except ModuleNotFoundError:  # running from a source checkout
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import quantization as Q
from paddle_tpu.hapi.engine import Engine


def main():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 4))

    qat = Q.QAT()            # default: int8, EMA activation scales,
    qat.quantize(net)        # per-channel weight scales
    net.train()

    eng = Engine(net, loss=paddle.nn.CrossEntropyLoss(),
                 optimizer=paddle.optimizer.Adam(
                     5e-3, parameters=net.parameters()))

    rng = np.random.default_rng(0)
    X = rng.standard_normal((512, 32)).astype("float32")
    y = (X[:, :8].sum(-1) > 0).astype("int64") + 2 * (X[:, 0] > 0)
    for step in range(60):
        loss, _ = eng.train_batch([paddle.to_tensor(X)],
                                  [paddle.to_tensor(y)])
        if step % 20 == 0:
            print(f"step {step}: loss {float(loss):.4f}")

    net.eval()
    fq_acc = (np.asarray(net(paddle.to_tensor(X)).numpy()).argmax(-1)
              == y).mean()

    qat.convert(net)         # int8 weights + scales; int8 x int8 matmul
    int8_acc = (np.asarray(net(paddle.to_tensor(X)).numpy()).argmax(-1)
                == y).mean()
    print(f"fake-quant acc {fq_acc:.3f} -> int8 serving acc {int8_acc:.3f}")


if __name__ == "__main__":
    main()
